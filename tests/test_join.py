"""Bulk kNN-join engine (knn_tpu.join): query-side double buffering
over the EXISTING kernels and sharded programs.

The acceptance surface this file pins:

- the bitwise oracle — ``mode="certified"`` joins equal the f64 oracle
  (and the looped certified path) across precisions x kernels and on
  the IVF tier; ``mode="stream"`` joins equal the looped ``search``
  at the same padded block shape across the metric matrix;
- the super-HBM boundary matrices: query budgets that hold A exactly /
  one-row-over / many-x over, and a corpus B over the per-host HBM
  budget, with every executed superblock / db-segment / dispatch count
  pinned against the analysis.hbm byte model (and the sweep-nesting
  order against plan_join);
- the CPU throughput acceptance: the double-buffered join beats the
  looped serving baseline on rows/s with a nonzero overlap_ratio;
- the MODEL_VERSION-7 join roofline: modeled db HBM bytes per query
  fall as 1/superblock_rows until bound_class flips off hbm_bound,
  and attributed join blocks validate against the roofline schema;
- the ``join`` bench-artifact validator (the refresher's refusal list).
"""

import time

import numpy as np
import pytest

from knn_tpu.analysis import hbm
from knn_tpu.join import (JOIN_MODES, JOIN_VERSION, default_plan,
                          knn_join, validate_join_block)
from knn_tpu.parallel import ShardedKNN, make_mesh

DIM = 16
DB_SHARDS = 2
MESH = (4, DB_SHARDS)  # 4 query shards x 2 db shards
QUERY_SHARDS = 4


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None]
          - queries.astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


def _db(rng, n, dim=DIM):
    return (rng.random((n, dim)) * 10).astype(np.float32)


@pytest.fixture
def corpus(rng):
    db = _db(rng, 600)
    db[200:220] = db[:20]  # exact duplicates across shard boundaries
    q = _db(rng, 70)
    return db, q


def _looped_search(prog, q, sb_rows, **kw):
    """The looped-serving reference at the SAME padded block shape the
    stream path dispatches (pad rows are ordinary queries whose outputs
    are sliced away) — the bitwise contract's other side."""
    ds, is_ = [], []
    for lo in range(0, q.shape[0], sb_rows):
        blk = q[lo:lo + sb_rows]
        valid = blk.shape[0]
        if valid < sb_rows:
            blk = np.pad(blk, ((0, sb_rows - valid), (0, 0)))
        d, i = prog.search(blk, **kw)
        ds.append(np.asarray(d)[:valid])
        is_.append(np.asarray(i)[:valid])
    return np.concatenate(ds), np.concatenate(is_)


# -- stream mode: bitwise vs looped serving, metric matrix ----------------
@pytest.mark.parametrize("metric", ["l2", "l1", "cosine", "dot"])
def test_stream_join_bitwise_equals_looped_search(corpus, metric):
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=7, metric=metric)
    d, i, st = knn_join(prog, q, mode="stream", superblock_rows=32)
    ref_d, ref_i = _looped_search(prog, q, 32)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)
    assert st["mode"] == "stream" and st["rows"] == q.shape[0]
    assert st["superblocks"] == st["dispatches"] == -(-q.shape[0] // 32)
    assert st["db_segments"] == 1  # resident B streams nothing
    assert st["order"] == "query_major"
    assert st["rows_per_s"] > 0


def test_stream_join_return_sqrt_matches_search(corpus):
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    d, i, _ = knn_join(prog, q, mode="stream", superblock_rows=24,
                       return_sqrt=True)
    ref_d, ref_i = _looped_search(prog, q, 24, return_sqrt=True)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)


# -- certified mode: the bitwise oracle across precisions x kernels ------
@pytest.mark.parametrize("precision", [None, "bf16x3", "int8", "int4"])
def test_certified_join_oracle_across_precisions(corpus, precision):
    db, q = corpus
    ref_d, ref_i = _oracle(db, q, 7)
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=7)
    kw = {"selector": "approx"}
    if precision is not None:
        kw["precision"] = precision
    d, i, st = knn_join(prog, q, mode="certified", superblock_rows=24,
                        **kw)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)
    # bitwise-equal to the looped certified path by construction
    ld, li = [], []
    for lo in range(0, q.shape[0], 24):
        dd, ii, _ = prog.search_certified(q[lo:lo + 24], **kw)
        ld.append(dd)
        li.append(ii)
    np.testing.assert_array_equal(d, np.concatenate(ld))
    np.testing.assert_array_equal(i, np.concatenate(li))
    assert st["overlap_ratio"] is None  # the certified loop: no pipeline


@pytest.mark.parametrize("kernel", ["tiled", "streaming", "fused"])
def test_certified_join_oracle_across_kernels(corpus, kernel):
    db, q = corpus
    ref_d, ref_i = _oracle(db, q, 5)
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    d, i, _ = knn_join(prog, q, mode="certified", superblock_rows=32,
                       selector="approx", kernel=kernel)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)


@pytest.mark.parametrize("metric", ["cosine", "dot"])
def test_certified_join_mips_cosine_fast_path(corpus, metric):
    """Satellite: the MIPS/cosine certified path (norm augmentation /
    unit rows at placement) joins bitwise with the looped certified
    call and ranks identically to the XLA search path."""
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=6, metric=metric)
    d, i, _ = knn_join(prog, q, mode="certified", superblock_rows=24,
                       selector="approx")
    ld, li = [], []
    for lo in range(0, q.shape[0], 24):
        dd, ii, _ = prog.search_certified(q[lo:lo + 24],
                                          selector="approx")
        ld.append(dd)
        li.append(ii)
    np.testing.assert_array_equal(d, np.concatenate(ld))
    np.testing.assert_array_equal(i, np.concatenate(li))
    ref_d, ref_i = _looped_search(prog, q, 24)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-5)


def test_certified_join_on_ivf_tier(rng):
    from knn_tpu.ivf.index import IVFIndex

    db = _db(rng, 800)
    q = _db(rng, 40)
    ref_d, ref_i = _oracle(db, q, 6)
    idx = IVFIndex(db, mesh=make_mesh(*MESH), k=6, seed=0)
    d, i, st = knn_join(idx, q, mode="certified", superblock_rows=16)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)
    assert st["superblocks"] == -(-40 // 16)
    # the probed tier has no resident placement to stream against
    with pytest.raises(ValueError, match="certified"):
        knn_join(idx, q, mode="stream")


# -- super-HBM A: query-budget boundary matrix ----------------------------
def test_query_budget_boundary_matrix(corpus):
    """Budget holds A exactly -> 1 superblock; one row over -> 2;
    many-x over -> the byte model's count.  Results invariant to the
    superblocking (indices exactly; distances to gemm-shape tolerance,
    the CPU caveat the serving engine documents)."""
    db, q = corpus  # 70 query rows
    n_a = q.shape[0]
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    ref_i = None
    ref_d = None
    cases = [
        (hbm.query_block_bytes(72, DIM), 1),    # holds all 70 (72 = 4x)
        (hbm.query_block_bytes(69, DIM), 2),    # one row short of A
        (hbm.query_block_bytes(16, DIM), 5),    # many-x over
    ]
    for budget, expect in cases:
        assert hbm.n_superblocks(n_a, DIM, budget,
                                 query_multiple=QUERY_SHARDS) == expect
        d, i, st = knn_join(prog, q, mode="stream",
                            query_budget_bytes=budget)
        assert st["superblocks"] == st["dispatches"] == expect
        assert st["plan"]["superblocks"] == expect
        if ref_i is None:
            ref_i, ref_d = i, d
        else:
            np.testing.assert_array_equal(i, ref_i)
            np.testing.assert_allclose(d, ref_d, rtol=1e-5)
    # a budget too small for even one query-shard multiple is loud
    with pytest.raises(ValueError, match="cannot hold"):
        knn_join(prog, q, mode="stream", query_budget_bytes=8)


# -- super-HBM B: host-RAM-tier corpus, both nesting orders ---------------
def test_superhbm_b_join_db_major_matches_byte_model_and_resident(rng):
    """B over the per-host HBM budget: the sweep nests db_major (each
    segment placed h2d ONCE), executed counts equal plan_join, and the
    result is bitwise-identical to the resident placement's looped
    search."""
    db = _db(rng, 400)
    q = _db(rng, 48)
    resident = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    budget = hbm.placement_bytes(64, DIM)
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5,
                      hbm_budget_bytes=budget)
    segs = hbm.n_sweeps(400, DIM, budget, shard_multiple=DB_SHARDS)
    assert segs >= 6  # genuinely many-x over
    d, i, st = knn_join(prog, q, mode="stream", superblock_rows=16)
    plan = default_plan(prog, 48, superblock_rows=16)
    assert plan["order"] == "db_major"  # B stream bytes dwarf A's
    assert st["order"] == plan["order"]
    assert st["superblocks"] == plan["superblocks"] == 3
    assert st["db_segments"] == plan["db_segments"] == segs
    assert st["dispatches"] == plan["dispatches"] == 3 * segs
    ref_d, ref_i = _looped_search(resident, q, 16)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)


def test_superhbm_b_join_query_major_single_superblock(rng):
    # one superblock makes query_major the byte-minimal order (s = 1:
    # A + B <= B + g*A for every g >= 1) — the other nesting path
    db = _db(rng, 400)
    q = _db(rng, 48)
    resident = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5,
                      hbm_budget_bytes=hbm.placement_bytes(64, DIM))
    d, i, st = knn_join(prog, q, mode="stream", superblock_rows=48)
    assert st["order"] == "query_major"
    assert st["superblocks"] == 1
    assert st["db_segments"] > 1
    assert st["dispatches"] == st["db_segments"]
    ref_d, ref_i = _looped_search(resident, q, 48)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)


# -- throughput acceptance (CPU) ------------------------------------------
def test_join_beats_looped_serving_on_cpu(rng):
    """ACCEPTANCE: on the CPU backend the double-buffered join moves
    more rows/s than looping the serving search over the same padded
    blocks, with a nonzero measured dispatch-timeline overlap."""
    n, dim, rows, sb, k = 8192, 32, 1024, 256, 8
    db = rng.normal(size=(n, dim)).astype(np.float32)
    q = rng.normal(size=(rows, dim)).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=k)

    def looped_rows_per_s():
        t0 = time.perf_counter()
        for lo in range(0, rows, sb):
            d, i = prog.search(q[lo:lo + sb])
            np.asarray(d)
            np.asarray(i)  # block per dispatch: the serving shape
        return rows / (time.perf_counter() - t0)

    knn_join(prog, q, mode="stream", superblock_rows=sb)  # warm
    looped_rows_per_s()  # warm
    # wall-clock comparison on a shared CPU box: retry the whole
    # best-of-3 duel a few times so one noisy scheduler quantum can't
    # fail the run — the join still has to win an identically-measured
    # round outright
    best_join = best_base = overlap = 0.0
    for _attempt in range(3):
        for _ in range(3):
            _, _, st = knn_join(prog, q, mode="stream", superblock_rows=sb)
            best_join = max(best_join, st["rows_per_s"])
            overlap = max(overlap, st["overlap_ratio"])
        best_base = max(best_base,
                        max(looped_rows_per_s() for _ in range(3)))
        if best_join >= best_base:
            break
    assert overlap > 0
    assert best_join >= best_base, (
        f"join {best_join:.0f} rows/s did not beat looped serving "
        f"{best_base:.0f} rows/s")


# -- env switches + argument validation -----------------------------------
def test_env_switches_drive_the_plan(corpus, monkeypatch):
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    monkeypatch.setenv("KNN_TPU_JOIN_SUPERBLOCK", "32")
    monkeypatch.setenv("KNN_TPU_JOIN_DEPTH", "3")
    _, _, st = knn_join(prog, q, mode="stream")
    assert st["superblock_rows"] == 32
    assert st["depth"] == 3
    monkeypatch.setenv("KNN_TPU_JOIN_SUPERBLOCK", "many")
    with pytest.raises(ValueError, match="KNN_TPU_JOIN_SUPERBLOCK"):
        knn_join(prog, q, mode="stream")


def test_join_argument_validation(corpus):
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    assert set(JOIN_MODES) == {"stream", "certified"}
    with pytest.raises(ValueError, match="unknown join mode"):
        knn_join(prog, q, mode="batch")
    with pytest.raises(ValueError, match="incompatible"):
        knn_join(prog, q[:, :8], mode="stream")
    with pytest.raises(ValueError, match="superblock_rows"):
        knn_join(prog, q, mode="stream", superblock_rows=0)
    # certified joins run the program's own certified path: k is pinned
    # at placement, a mismatching override refuses loudly
    with pytest.raises(ValueError, match="program.k"):
        knn_join(prog, q, mode="certified", k=9)


# -- the MODEL_VERSION-7 join roofline ------------------------------------
def test_join_model_db_bytes_amortize_until_bound_flips():
    """The pinned amortization law: modeled db HBM bytes per query fall
    as 1/superblock_rows while the block stays hbm_bound, until the
    bound flips to a term that stops shrinking (custom peaks make the
    flip land inside the sweep)."""
    from knn_tpu.obs import roofline

    peaks = {"bf16_flops": 400e12, "int8_flops": 800e12,
             "hbm_gbps": 800.0, "vpu_ops": 40e12, "h2d_gbps": 50.0}
    sbs = [128, 512, 2048, 8192, 32768, 131072]
    models = [roofline.join_cost_model(
        n_a=1_000_000, n_b=1_000_000, d=128, k=100, superblock_rows=sb,
        selector="exact", device_kind="TPU v5e", peaks=peaks)
        for sb in sbs]
    per_q = [m["join"]["db_bytes_per_query"] for m in models]
    bounds = [m["bound_class"] for m in models]
    assert bounds[0] == "hbm_bound"
    assert bounds[-1] != "hbm_bound"  # the flip the regime exists for
    for j in range(1, len(sbs)):
        # exact 1/S law: same db bytes spread over more queries
        np.testing.assert_allclose(per_q[j] * sbs[j],
                                   per_q[0] * sbs[0], rtol=1e-12)
    # once flipped, ceiling rows/s stops improving with superblock size
    flip = bounds.index(next(b for b in bounds if b != "hbm_bound"))
    assert models[flip]["ceiling_qps"] is not None


def test_join_model_block_validates_and_h2d_can_bind():
    from knn_tpu.obs import roofline

    model = roofline.join_cost_model(
        n_a=65536, n_b=1_000_000, d=128, k=100, superblock_rows=4096,
        selector="exact", device_kind="TPU v5e")
    block = roofline.attribute(model, 1e5)
    assert roofline.validate_block(block) == []
    assert block["terms"]["h2d"]["overlapped"] is True
    assert block["join"]["superblocks"] == 16
    # a starved host link makes the stream the bound
    slow = roofline.join_cost_model(
        n_a=65536, n_b=1_000_000, d=128, k=100, superblock_rows=4096,
        selector="exact", device_kind="TPU v5e",
        peaks={**roofline.PEAKS_BY_KIND["TPU v5e"], "h2d_gbps": 1e-3})
    assert slow["bound_class"] == "h2d_bound"
    assert roofline.validate_block(
        roofline.attribute(slow, 1e3)) == []


# -- the join bench-artifact validator ------------------------------------
def test_validate_join_block():
    block = {
        "join_version": JOIN_VERSION, "mode": "stream", "rows": 4096,
        "k": 10, "superblock_rows": 512, "depth": 2,
        "order": "query_major", "superblocks": 8, "db_segments": 1,
        "dispatches": 8, "rows_per_s": 12345.6, "overlap_ratio": 0.8,
    }
    assert validate_join_block(block) == []
    broken = {k: v for k, v in block.items() if k != "rows_per_s"}
    assert any("rows_per_s" in v for v in validate_join_block(broken))
    # a block that recorded its own failure is exempt — an honest error
    # field beats a refused line
    assert validate_join_block({"error": "join sweep failed"}) == []


def test_default_plan_is_jax_free_truth(corpus):
    db, q = corpus
    prog = ShardedKNN(db, mesh=make_mesh(*MESH), k=5)
    plan = default_plan(prog, q.shape[0], superblock_rows=32)
    ref = hbm.plan_join(q.shape[0], 600, DIM, superblock_rows=32,
                        db_segment_rows=0)
    for key in ("order", "superblocks", "db_segments", "dispatches",
                "h2d_bytes"):
        assert plan[key] == ref[key]
    _, _, st = knn_join(prog, q, mode="stream", superblock_rows=32)
    for key in ("superblocks", "db_segments", "dispatches"):
        assert st[key] == plan[key]
