"""Sharded certified-exact path: coarse selector (approx/pallas/exact) on
each db shard, lexicographic merge, float64 refine, distributed count-below
certificate (psum over the db axis), exact fallback — must equal the
float64 oracle on every mesh shape."""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu.models.classifier import knn_predict
from knn_tpu.parallel import ShardedKNN, make_mesh
from knn_tpu.pipeline import run_job
from knn_tpu.utils.config import JobConfig


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None] - queries.astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


@pytest.fixture
def data(rng):
    db = rng.normal(size=(1100, 16)).astype(np.float32) * 10
    db[500:550] = db[:50]  # ties across shard boundaries
    queries = rng.normal(size=(37, 16)).astype(np.float32) * 10
    return db, queries


@pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4), (1, 8)])
@pytest.mark.parametrize("selector", ["approx", "exact"])
def test_sharded_certified_matches_oracle(data, mesh_shape, selector):
    db, queries = data
    ref_d, ref_i = _oracle(db, queries, 7)
    prog = ShardedKNN(db, mesh=make_mesh(*mesh_shape), k=7)
    d, i, stats = prog.search_certified(queries, selector=selector)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)
    assert stats["certified"] + stats["fallback_queries"] == queries.shape[0]


def test_sharded_certified_pallas_selector(rng):
    # pallas bins need >= k*BIN_W rows per shard: use a bigger db, 2 shards
    db = rng.normal(size=(4 * 128 * 5, 8)).astype(np.float32)
    queries = rng.normal(size=(16, 8)).astype(np.float32)
    ref_d, ref_i = _oracle(db, queries, 4)
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=4)
    d, i, stats = prog.search_certified(queries, selector="pallas")
    np.testing.assert_array_equal(i, ref_i)


def test_predict_certified_matches_exact_predict(data):
    db, queries = data
    labels = (np.arange(db.shape[0]) % 5).astype(np.int32)
    mesh = make_mesh(2, 4)
    prog = ShardedKNN(db, mesh=mesh, k=9, labels=labels, num_classes=5)
    ref = np.asarray(
        knn_predict(jnp.asarray(db), jnp.asarray(labels), jnp.asarray(queries),
                    k=9, num_classes=5)
    )
    got, stats = prog.predict_certified(queries)
    np.testing.assert_array_equal(got, ref)


def test_certified_rejects_non_l2(data):
    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(8, 1), k=3, metric="l1")
    with pytest.raises(ValueError, match="l2, cosine and dot"):
        prog.search_certified(queries)


@pytest.mark.parametrize("metric", ["l2", "cosine"])
def test_pipeline_certified_mode(tmp_path, rng, metric):
    # --mode certified end to end through run_job, both supported
    # metrics (cosine's config gate opened in round 4): labels must
    # match the exact pipeline and the stats invariants must hold
    from knn_tpu.data.datasets import make_blobs, save_labeled_csv, save_unlabeled_csv

    feats, labels = make_blobs(300, 6, 3, cluster_std=0.3, seed=9)
    paths = {
        "train": str(tmp_path / "train.csv"),
        "val": str(tmp_path / "val.csv"),
        "test": str(tmp_path / "test.csv"),
    }
    save_labeled_csv(paths["train"], feats[:200], labels[:200])
    save_labeled_csv(paths["val"], feats[200:250], labels[200:250])
    save_unlabeled_csv(paths["test"], feats[250:])

    def cfg(mode):
        return JobConfig(
            train_file=paths["train"], test_file=paths["test"], val_file=paths["val"],
            output_file=str(tmp_path / f"out_{mode}.csv"), k=5,
            metric=metric, query_shards=4, db_shards=2, mode=mode,
        )

    exact = run_job(cfg("exact"))
    cert = run_job(cfg("certified"))
    np.testing.assert_array_equal(exact.test_labels, cert.test_labels)
    np.testing.assert_array_equal(exact.val_labels, cert.val_labels)

    # --mode certified observability: stats land on the result and in metrics()
    assert exact.certified_stats is None
    assert "certified_stats" not in exact.metrics()
    stats = cert.certified_stats
    assert stats is not None
    n_queries = cert.n_test + cert.n_val
    assert stats["certified"] + stats["fallback_queries"] == n_queries
    assert cert.metrics()["certified_stats"] == stats


def test_config_certified_metric_gate():
    with pytest.raises(ValueError, match="requires the l2 or cosine"):
        JobConfig(mode="certified", metric="l1")
    JobConfig(mode="certified", metric="cosine")  # supported since round 4
    # case is normalized at the config boundary so downstream dispatch
    # (ShardedKNN's cosine placement normalization) can't be bypassed
    assert JobConfig(mode="certified", metric="Cosine").metric == "cosine"
    with pytest.raises(ValueError, match="mode"):
        JobConfig(mode="fast")
    with pytest.raises(ValueError, match="selector"):
        JobConfig(selector="magic")


@pytest.mark.parametrize("batch_size", [16, 37, 64])
def test_sharded_certified_batched_matches_unbatched(data, batch_size):
    # pipelined batching is an execution strategy, not a semantic knob:
    # results must be identical for any batch size, including non-dividing
    # and larger-than-Q sizes
    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=7)
    ref_d, ref_i, _ = prog.search_certified(queries)
    d, i, stats = prog.search_certified(queries, batch_size=batch_size)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)
    assert stats["certified"] + stats["fallback_queries"] == queries.shape[0]


def test_pallas_certified_beats_f32_cancellation(rng):
    # at tiny distances vs large norms the expanded-square f32 "exact"
    # path loses ~all its bits (catastrophic cancellation); the pallas
    # path's direct-difference rank + tie runs + repair must still match
    # the FLOAT64 oracle (which the f32 exact path here cannot)
    from knn_tpu.ops.certified import host_exact_knn

    db = rng.normal(size=(3000, 10)).astype(np.float32) * 10
    db[200:260] = db[:60]          # duplicate ties
    db[500:540] = db[0] + 0.0001   # 40-way pileup nearer than db[0] itself
    queries = np.vstack([
        db[0][None] + 0.01,
        rng.normal(size=(15, 10)).astype(np.float32) * 10,
    ]).astype(np.float32)
    od, oi = host_exact_knn(db, queries, 12)
    for mesh_shape in [(8, 1), (2, 4)]:
        prog = ShardedKNN(db, mesh=make_mesh(*mesh_shape), k=12)
        for wd in (True, False):
            d, i, stats = prog.search_certified(
                queries, selector="pallas", tile_n=256, return_distances=wd
            )
            np.testing.assert_array_equal(i, oi)
            assert (d is None) == (not wd)


@pytest.mark.parametrize("selector", ["approx", "exact", "pallas"])
def test_return_distances_false_uniform_contract(data, selector):
    # (None, idx, stats) for EVERY selector — not a pallas-only behavior
    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(2, 4), k=7)
    ref_d, ref_i = _oracle(db, queries, 7)
    kwargs = {"tile_n": 256} if selector == "pallas" else {}
    d, i, stats = prog.search_certified(
        queries, selector=selector, return_distances=False, **kwargs
    )
    assert d is None
    np.testing.assert_array_equal(i, ref_i)


def test_adaptive_gap_threshold_kills_false_alarms(rng):
    # a db row sits WITHIN the count pass's f32 tolerance of d_k: the old
    # fixed threshold (d_k + tol) counted it and false-alarmed into the
    # exact fallback; the adaptive form finds the first >2*tol gap at
    # rank j >= k inside the margin window and counts against its
    # midpoint instead — certified, zero fallbacks, result still exact
    from knn_tpu.ops.certified import certification_tolerance

    dim, k = 4, 3
    base = 3000.0
    db = rng.normal(size=(512, dim)).astype(np.float32)
    db = db / np.linalg.norm(db, axis=-1, keepdims=True)
    radii = np.linspace(base, base * 1.4, 512).astype(np.float32)
    db = db * radii[:, None]
    queries = np.zeros((5, dim), dtype=np.float32)
    tol = certification_tolerance(queries, db)[0]
    assert tol > 1.0  # the scale makes the f32 slack material
    # plant ranks 0..k: the (k+1)-th neighbor within tol/4 of the k-th,
    # then a clean > 2*tol gap before everything else
    r_k = base
    tight_rows = np.eye(dim, dtype=np.float32)[:1] * np.sqrt(
        np.array([r_k**2 - 3, r_k**2 - 2, r_k**2 - 1, r_k**2,
                  r_k**2 + tol / 4], dtype=np.float64)
    ).astype(np.float32)[:, None]
    db[:5] = tight_rows
    db[5:] = db[5:] * 1.2  # push the rest past a comfortable gap
    ref_d, ref_i = _oracle(db, queries, k)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=k)
    d, i, stats = prog.search_certified(queries, selector="exact", margin=8)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)
    assert stats["fallback_queries"] == 0


def test_certified_counted_margin_zero(rng):
    # m == k: the adaptive gap search has no window — must degrade to the
    # fixed threshold without indexing past the candidate array
    db = rng.normal(size=(64, 8)).astype(np.float32)
    queries = rng.normal(size=(5, 8)).astype(np.float32)
    ref_d, ref_i = _oracle(db, queries, 4)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=4)
    d, i, stats = prog.search_certified(queries, selector="exact", margin=0)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)


def _cosine_oracle(db, queries, k):
    """float64 cosine-distance lexicographic top-k on the f32 unit-
    normalized problem (the space search_certified certifies)."""
    def unit(x):
        n = np.linalg.norm(x.astype(np.float64), axis=-1, keepdims=True)
        return (x / np.maximum(n, 1e-300)).astype(np.float32)

    dbn, qn = unit(db).astype(np.float64), unit(queries).astype(np.float64)
    d = 1.0 - qn @ dbn.T
    idx = np.lexsort((np.broadcast_to(np.arange(db.shape[0]), d.shape), d),
                     axis=-1)[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


@pytest.mark.parametrize("selector", ["exact", "approx", "pallas"])
def test_certified_cosine_matches_oracle(rng, selector):
    # VERDICT r4 item: cosine certified search through the LIBRARY path
    # (db normalized at placement, queries at entry, l2 certificate on
    # unit vectors) must match the float64 cosine oracle, with distances
    # returned in 1-similarity units
    db = (rng.normal(size=(900, 24)) * np.linspace(
        0.5, 3.0, 900)[:, None]).astype(np.float32)  # varied row norms
    queries = (rng.normal(size=(17, 24)) * 2).astype(np.float32)
    k = 7
    ref_d, ref_i = _cosine_oracle(db, queries, k)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=k, metric="cosine")
    d, i, stats = prog.search_certified(queries, selector=selector, margin=8)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-6)
    assert stats["certified"] + stats["fallback_queries"] == 17


def test_certified_cosine_plain_search_agrees(rng):
    # placement-time normalization must not change plain cosine search
    # (pairwise_cosine re-normalizes idempotently)
    db = (rng.normal(size=(300, 12)) * 5).astype(np.float32)
    queries = rng.normal(size=(9, 12)).astype(np.float32)
    a = ShardedKNN(db, mesh=make_mesh(1, 2), k=5, metric="cosine")
    _, ref_i = _cosine_oracle(db, queries, 5)
    _, ia = a.search(queries)
    np.testing.assert_array_equal(np.asarray(ia), ref_i)


def test_certified_l1_still_rejected(rng):
    db = rng.normal(size=(64, 8)).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=3, metric="l1")
    with pytest.raises(ValueError, match="l2, cosine and dot"):
        prog.search_certified(rng.normal(size=(2, 8)).astype(np.float32))




def test_certified_pallas_multitile_multichunk_sharded(rng):
    # the gist-shaped corner: dim > DIM_CHUNK (multi-chunk scratch
    # accumulation) x multiple db tiles per shard x 2 db shards, grouped
    # binning — every structural axis of the kernel at once, vs the
    # float64 oracle
    db = rng.normal(size=(6 * 256 + 40, 200)).astype(np.float32) * 5
    queries = rng.normal(size=(9, 200)).astype(np.float32) * 5
    ref_d, ref_i = _oracle(db, queries, 6)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=6)
    d, i, stats = prog.search_certified(queries, selector="pallas",
                                        tile_n=256, margin=8)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)
