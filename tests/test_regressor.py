"""KNNRegressor tests (capability extension over the reference, which only
classifies — SURVEY.md §2)."""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu.models.regressor import KNNRegressor, knn_regress


def test_uniform_weights_match_numpy(rng):
    X = rng.normal(size=(120, 6)).astype(np.float32)
    y = rng.normal(size=120).astype(np.float32)
    Q = rng.normal(size=(15, 6)).astype(np.float32)
    reg = KNNRegressor(k=7).fit(X, y)
    pred = np.asarray(reg.predict(Q))
    # numpy oracle
    d = ((X.astype(np.float64)[None] - Q.astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :7]
    want = y[idx].mean(-1)
    np.testing.assert_allclose(pred, want, rtol=1e-5, atol=1e-6)


def test_distance_weights_interpolate(rng):
    # exact hit: distance-weighted prediction must return that target
    X = rng.normal(size=(50, 4)).astype(np.float32)
    y = rng.normal(size=50).astype(np.float32)
    reg = KNNRegressor(k=5, weights="distance").fit(X, y)
    pred = np.asarray(reg.predict(X[:8]))
    np.testing.assert_allclose(pred, y[:8], rtol=1e-3)


def test_multioutput_targets(rng):
    X = rng.normal(size=(60, 5)).astype(np.float32)
    y = rng.normal(size=(60, 3)).astype(np.float32)
    pred = np.asarray(KNNRegressor(k=4).fit(X, y).predict(X[:10]))
    assert pred.shape == (10, 3)
    d = ((X.astype(np.float64)[None] - X[:10].astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :4]
    np.testing.assert_allclose(pred, y[idx].mean(1), rtol=1e-5, atol=1e-6)


def test_tiled_matches_untiled(rng):
    X = rng.normal(size=(200, 8)).astype(np.float32)
    y = rng.normal(size=200).astype(np.float32)
    Q = rng.normal(size=(9, 8)).astype(np.float32)
    a = np.asarray(KNNRegressor(k=6).fit(X, y).predict(Q))
    b = np.asarray(KNNRegressor(k=6, train_tile=33).fit(X, y).predict(Q))
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_regressor_errors(rng):
    X = rng.normal(size=(10, 3)).astype(np.float32)
    y = rng.normal(size=10).astype(np.float32)
    with pytest.raises(RuntimeError, match="fit"):
        KNNRegressor(k=2).predict(X)
    with pytest.raises(ValueError, match="k="):
        KNNRegressor(k=11).fit(X, y)
    with pytest.raises(ValueError, match="weights"):
        knn_regress(jnp.asarray(X), jnp.asarray(y), jnp.asarray(X[:2]), k=2, weights="quadratic")


def test_meshed_regressor_matches_single_device(rng):
    from knn_tpu.parallel import make_mesh

    X = rng.normal(size=(200, 10)).astype(np.float32)
    y = rng.normal(size=(200,)).astype(np.float32)
    Q = rng.normal(size=(30, 10)).astype(np.float32)
    for weights, rtol in (("uniform", 0), ("distance", 1e-4)):
        # uniform: identical neighbor sets -> identical means.  distance:
        # the sharded matmul partitions the reduction differently, so
        # distances (and the 1/d weights) differ by float32 ulps
        ref = np.asarray(KNNRegressor(k=6, weights=weights).fit(X, y).predict(Q))
        got = np.asarray(
            KNNRegressor(k=6, weights=weights, mesh=make_mesh(4, 2), merge="ring")
            .fit(X, y).predict(Q)
        )
        np.testing.assert_allclose(got, ref, rtol=rtol)


def test_distance_weights_use_unsquared_l2(rng):
    # VERDICT r2 weak #6: weights="distance" must weight by 1/d (true L2),
    # not 1/d^2 — the search returns squared distances for ranking speed
    import numpy as np

    from knn_tpu.models.regressor import KNNRegressor

    X = np.array([[0.0], [3.0], [9.0]], dtype=np.float32)
    y = np.array([0.0, 1.0, 2.0], dtype=np.float32)
    q = np.array([[1.0]], dtype=np.float32)  # d = [1, 2, 8]
    pred = float(
        KNNRegressor(k=3, weights="distance").fit(X, y).predict(q)[0]
    )
    w = 1.0 / np.array([1.0, 2.0, 8.0])
    expect = float((w / w.sum() @ y))
    assert abs(pred - expect) < 1e-6
