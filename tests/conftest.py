"""Test harness: force an 8-device CPU mesh so every multi-device test runs
on virtual CPU devices — these play the role MPI ranks play in the reference
(SURVEY.md §4) — without touching TPU hardware.

Two mechanisms, because the TPU environment may inject a PJRT plugin via
sitecustomize *before* this file runs (so env vars alone come too late
there, and config updates alone don't cover fresh subprocesses):
  1. env vars, for any subprocess the tests spawn;
  2. ``jax.config.update``, which wins in this process as long as no
     backend has been initialized yet (JAX initializes them lazily).
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# isolate the autotuner's persisted winner cache: a developer machine's
# real ~/.cache/knn_tpu/autotune.json must never steer test kernels
# (tests that exercise the cache pass explicit paths / their own env)
os.environ["KNN_TPU_TUNE_CACHE"] = os.path.join(
    tempfile.mkdtemp(prefix="knn_tpu_test_tune_"), "autotune.json")
# isolate the telemetry env knobs the same way: the suite assumes the
# default-on registry, no ambient JSONL sink, the default rotation cap,
# the default SLO objectives, and a DISARMED flight recorder — an
# ambient KNN_TPU_POSTMORTEM_DIR would write a postmortem bundle on
# every test that trips an SLO breach (tests that exercise these set
# their own paths/values explicitly)
for _knob in ("KNN_TPU_OBS", "KNN_TPU_OBS_LOG",
              "KNN_TPU_OBS_LOG_MAX_BYTES", "KNN_TPU_SLO_CONFIG",
              "KNN_TPU_POSTMORTEM_DIR", "KNN_TPU_POSTMORTEM_KEEP",
              # an ambient prune threshold would silently shrink every
              # autotune grid in the suite; an ambient overlap switch
              # would flip every certified search onto the pipelined
              # path (tests that exercise them set their own values)
              "KNN_TPU_TUNE_PRUNE", "KNN_TPU_PIPELINE_OVERLAP",
              "KNN_TPU_PIPELINE_DEPTH"):
    os.environ.pop(_knob, None)
# isolate the admission-control and loadgen knobs: a developer shell's
# ambient KNN_TPU_ADMISSION_* would silently flip every QueryQueue in
# the suite onto the admission path (AdmissionConfig.from_env treats
# ANY set knob as an opt-in), breaking the disabled-mode
# bitwise-identity pins (tests that exercise admission build explicit
# AdmissionConfig objects or set their own env)
for _knob in [k for k in os.environ
              if k.startswith(("KNN_TPU_ADMISSION_", "KNN_TPU_LOADGEN_",
                               "KNN_BENCH_KNEE_"))]:
    os.environ.pop(_knob, None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # older jax: the XLA_FLAGS path above covers it

import numpy as np
import pytest

assert len(jax.devices()) >= 8, "test harness requires 8 virtual CPU devices"


@pytest.fixture
def rng():
    return np.random.default_rng(0)
