"""Test harness: force an 8-device CPU mesh so every multi-device test runs
on virtual CPU devices — these play the role MPI ranks play in the reference
(SURVEY.md §4) — without touching TPU hardware.

Two mechanisms, because the TPU environment may inject a PJRT plugin via
sitecustomize *before* this file runs (so env vars alone come too late
there, and config updates alone don't cover fresh subprocesses):
  1. env vars, for any subprocess the tests spawn;
  2. ``jax.config.update``, which wins in this process as long as no
     backend has been initialized yet (JAX initializes them lazily).
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# Switch isolation is GENERATED from the central env-switch catalog
# (knn_tpu.analysis.switches — jax-free, so this import is safe before
# the backend config below): every cataloged mutable switch plus any
# ambient variable under a cataloged family prefix is scrubbed, so a
# developer shell's KNN_TPU_*/KNN_BENCH_* can never silently steer the
# suite.  Never hand-list switches here again — declare them in the
# catalog and isolation follows on the next run (the switch-lockstep
# checker fails the lint if this derivation is removed).  Tests that
# exercise a switch set their own value AFTER this scrub, per-test.
from knn_tpu.analysis.switches import isolation_names

for _knob in isolation_names(os.environ):
    os.environ.pop(_knob, None)
# isolate the autotuner's persisted winner cache: a developer machine's
# real ~/.cache/knn_tpu/autotune.json must never steer test kernels
# (tests that exercise the cache pass explicit paths / their own env).
# Set AFTER the scrub — this is the suite's own value, not an ambient one.
os.environ["KNN_TPU_TUNE_CACHE"] = os.path.join(
    tempfile.mkdtemp(prefix="knn_tpu_test_tune_"), "autotune.json")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # older jax: the XLA_FLAGS path above covers it

import numpy as np
import pytest

assert len(jax.devices()) >= 8, "test harness requires 8 virtual CPU devices"


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def mh_spawn(tmp_path):
    """The 2-process CPU ``jax.distributed`` subprocess harness
    (tests/mh_harness.py), pre-gated on the coordinator/KV-store probe:
    ``mh_spawn(child_src, n_proc=2)`` spawns the processes and returns
    {pid: parsed RESULT json}, skipping ONLY when the harness itself
    probes red (the distributed-init probe fails on this jaxlib)."""
    import mh_harness

    def spawn(child_src: str, n_proc: int = 2, timeout_s: int = 180):
        verdict = mh_harness.distributed_init_supported()
        if not verdict["ok"]:
            pytest.skip("jax.distributed coordinator/KV store "
                        f"unsupported: {verdict['reason']}")
        return mh_harness.spawn_jax_procs(tmp_path, child_src, n_proc,
                                          timeout_s)

    return spawn
