"""The static-analysis suite itself (knn_tpu.analysis, docs/ANALYSIS.md):
framework semantics (registry, suppression grammar, crash-to-finding),
one known-bad and one known-good fixture per checker, the geometry/width
mirror pins of the VMEM model, the autotuner's runtime VMEM gate, the
runtime lock-order (deadlock) harness over the real serving stack, and
the ``cli lint`` subprocess exit-code contract.

The fixture trees seed deliberate violations (uncataloged switches,
phantom metrics, unlocked mutations) — tests/ is exempt from the lint's
source roots precisely so these seeds never trip the real gate.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from knn_tpu import analysis
from knn_tpu.analysis import switches as sw
from knn_tpu.analysis import vmem
from knn_tpu.analysis.check_vmem import grid_findings
from knn_tpu.analysis.core import CHECKERS, load_suppressions
from knn_tpu.analysis.lockorder import (
    InstrumentedLock,
    LockOrderRecorder,
    instrument,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_tree(root, files):
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))


def run_on(root, checker):
    return analysis.run(str(root), names=[checker])


# --- framework ----------------------------------------------------------
def test_registry_has_the_six_checkers():
    assert set(CHECKERS) == {"switch-lockstep", "metric-lockstep",
                             "locked-mutation", "jax-hygiene",
                             "vmem-budget", "artifact-lockstep"}


def test_unknown_checker_raises():
    with pytest.raises(ValueError, match="unknown checker"):
        analysis.run(REPO, names=["no-such-checker"])


def test_syntax_error_becomes_finding(tmp_path):
    write_tree(tmp_path, {"knn_tpu/broken.py": "def f(:\n"})
    rep = run_on(tmp_path, "locked-mutation")
    assert not rep.ok
    assert any(f.checker == "framework" and "does not parse" in f.message
               for f in rep.findings)


def test_text_only_pass_skips_the_parse(tmp_path):
    """A pass selecting only non-AST checkers (the lint_metric_names
    shim's metric-lockstep run) keeps the original text lint's
    tolerance of unparseable files — no whole-tree parse, no
    syntax-error findings that would be wrong for a pass in which no
    AST checker ran."""
    write_tree(tmp_path, {"knn_tpu/broken.py": "def f(:\n"})
    rep = run_on(tmp_path, "metric-lockstep")
    assert rep.ok, [f.message for f in rep.findings]
    rep2 = run_on(tmp_path, "vmem-budget")
    assert not any(f.checker == "framework" for f in rep2.findings)


def test_checker_crash_becomes_finding(tmp_path):
    write_tree(tmp_path, {"knn_tpu/ok.py": "x = 1\n"})

    def boom(ctx):
        raise RuntimeError("kaboom")

    CHECKERS["test-boom"] = (boom, "always crashes")
    try:
        rep = analysis.run(str(tmp_path), names=["test-boom"])
    finally:
        del CHECKERS["test-boom"]
    assert not rep.ok
    assert any("checker crashed" in f.message and "kaboom" in f.message
               for f in rep.findings)


def test_report_json_shape(tmp_path):
    write_tree(tmp_path, {"knn_tpu/ok.py": "x = 1\n"})
    rep = run_on(tmp_path, "locked-mutation")
    d = rep.as_dict()
    assert d["ok"] is True
    assert d["checkers"] == ["locked-mutation"]
    assert d["findings"] == [] and d["suppressed"] == 0
    assert "OK" in rep.render_text()


# --- suppression grammar ------------------------------------------------
def _sup_file(tmp_path, payload):
    p = tmp_path / "sup.json"
    p.write_text(json.dumps(payload))
    return str(p)


def test_suppression_requires_written_justification(tmp_path):
    path = _sup_file(tmp_path, {"suppressions": [
        {"checker": "jax-hygiene", "path": "a.py", "contains": "x",
         "justification": "because"}]})  # < 10 chars
    sups, errors = load_suppressions(path)
    assert sups == []
    assert any("justification" in e.message for e in errors)


def test_suppression_rejects_unknown_keys_and_shapes(tmp_path):
    path = _sup_file(tmp_path, {"suppressions": [
        {"checker": "jax-hygiene", "line": 3,
         "justification": "long enough justification"}]})
    _, errors = load_suppressions(path)
    assert any("unknown keys" in e.message for e in errors)
    path2 = _sup_file(tmp_path, {"not-suppressions": []})
    _, errors2 = load_suppressions(path2)
    assert any("top level" in e.message for e in errors2)


def test_stale_suppression_is_a_finding(tmp_path):
    write_tree(tmp_path, {"knn_tpu/ok.py": "x = 1\n"})
    path = _sup_file(tmp_path, {"suppressions": [
        {"checker": "locked-mutation", "path": "knn_tpu/gone.py",
         "contains": "self._x",
         "justification": "outlived the code it excused"}]})
    rep = analysis.run(str(tmp_path), names=["locked-mutation"],
                       suppressions_path=path)
    assert not rep.ok
    assert any("stale suppression" in f.message for f in rep.findings)


def test_subset_run_does_not_condemn_other_checkers_suppressions(
        tmp_path):
    """A metric-lockstep-only pass (the lint_metric_names shim) must not
    flag the jax-hygiene suppressions as stale."""
    write_tree(tmp_path, {"knn_tpu/ok.py": "x = 1\n"})
    path = _sup_file(tmp_path, {"suppressions": [
        {"checker": "jax-hygiene", "path": "knn_tpu/obs/trace.py",
         "contains": "time.time",
         "justification": "wall timestamp by contract, never differenced"
         }]})
    rep = analysis.run(str(tmp_path), names=["locked-mutation"],
                       suppressions_path=path)
    assert rep.ok, [f.message for f in rep.findings]
    # ...but an entry naming a checker that doesn't exist is stale in
    # EVERY pass
    path2 = _sup_file(tmp_path, {"suppressions": [
        {"checker": "no-such-checker", "path": "", "contains": "x",
         "justification": "points at nothing that could ever match"}]})
    rep2 = analysis.run(str(tmp_path), names=["locked-mutation"],
                        suppressions_path=path2)
    assert any("stale suppression" in f.message for f in rep2.findings)


def test_matching_suppression_silences_and_counts(tmp_path):
    write_tree(tmp_path, {"knn_tpu/mod.py": '''
        import time

        def f():
            return time.time()
        '''})
    rep = analysis.run(str(tmp_path), names=["jax-hygiene"])
    assert not rep.ok and rep.findings[0].symbol == "time.time"
    path = _sup_file(tmp_path, {"suppressions": [
        {"checker": "jax-hygiene", "path": "knn_tpu/mod.py",
         "contains": "time.time",
         "justification": "fixture wall timestamp, never differenced"}]})
    rep2 = analysis.run(str(tmp_path), names=["jax-hygiene"],
                        suppressions_path=path)
    assert rep2.ok and rep2.suppressed == 1


# --- switch-lockstep ----------------------------------------------------
ALL_SWITCH_NAMES = "\n".join(s.name for s in sw.SWITCHES)

GOOD_SWITCH_TREE = {
    # a CODE literal (not a docstring): consumption is judged on code
    "knn_tpu/mod.py": f'_READS = """\n{ALL_SWITCH_NAMES}\n"""\n',
    "docs/SWITCHES.md": ALL_SWITCH_NAMES + "\n",
    "tests/conftest.py": """
        import os

        from knn_tpu.analysis.switches import isolation_names

        for k in isolation_names(os.environ):
            os.environ.pop(k, None)
        """,
}


def test_switch_checker_passes_known_good_tree(tmp_path):
    write_tree(tmp_path, GOOD_SWITCH_TREE)
    rep = run_on(tmp_path, "switch-lockstep")
    assert rep.ok, [f.message for f in rep.findings]


def test_switch_checker_flags_uncataloged_switch(tmp_path):
    tree = dict(GOOD_SWITCH_TREE)
    tree["knn_tpu/rogue.py"] = '''
        import os

        FLAG = os.environ.get("KNN_TPU_TOTALLY_BOGUS")
        '''
    write_tree(tmp_path, tree)
    rep = run_on(tmp_path, "switch-lockstep")
    assert not rep.ok
    hits = [f for f in rep.findings if f.symbol == "KNN_TPU_TOTALLY_BOGUS"]
    assert hits and "not declared in the switch catalog" in hits[0].message
    assert hits[0].path == os.path.join("knn_tpu", "rogue.py")


def test_switch_checker_flags_phantom_doc_and_missing_doc(tmp_path):
    tree = dict(GOOD_SWITCH_TREE)
    tree["docs/SWITCHES.md"] = (
        ALL_SWITCH_NAMES.replace("KNN_TPU_OBS_LOG\n", "")
        + "\nKNN_BENCH_PHANTOM_KNOB\n")
    write_tree(tmp_path, tree)
    rep = run_on(tmp_path, "switch-lockstep")
    msgs = [f.message for f in rep.findings]
    assert any("KNN_TPU_OBS_LOG is missing from the docs" in m
               for m in msgs)
    assert any("KNN_BENCH_PHANTOM_KNOB" in m and "phantom" in m
               for m in msgs)


def test_switch_checker_flags_handlisted_conftest(tmp_path):
    tree = dict(GOOD_SWITCH_TREE)
    tree["tests/conftest.py"] = '''
        import os

        os.environ.pop("KNN_TPU_OBS", None)  # hand-listed, not derived
        '''
    write_tree(tmp_path, tree)
    rep = run_on(tmp_path, "switch-lockstep")
    assert any("isolation_names" in f.message for f in rep.findings)


def test_isolation_names_generated_from_catalog():
    names = sw.isolation_names()
    # every concrete isolate=True switch, no family prefixes
    assert "KNN_TPU_OBS" in names and "KNN_BENCH_N" in names
    assert not any(n.endswith("_") for n in names)
    # ambient members of an isolated family prefix are swept in
    env = {"KNN_BENCH_PALLAS_FUTURE_KNOB": "1", "UNRELATED": "x"}
    names_env = sw.isolation_names(env)
    assert "KNN_BENCH_PALLAS_FUTURE_KNOB" in names_env
    assert "UNRELATED" not in names_env
    assert names_env == sorted(set(names_env))


def test_switch_checker_docstring_mention_is_not_consumption(tmp_path):
    """A switch named ONLY in a docstring reads as never-consumed: a
    deleted env read whose docstring survives must not keep a phantom
    catalog row alive."""
    tree = dict(GOOD_SWITCH_TREE)
    tree["knn_tpu/mod.py"] = (
        f'"""Docs mention KNN_TPU_OBS_LOG here."""\n_READS = """\n'
        + ALL_SWITCH_NAMES.replace("KNN_TPU_OBS_LOG\n", "")
        + '\n"""\n')
    write_tree(tmp_path, tree)
    rep = run_on(tmp_path, "switch-lockstep")
    assert any(f.symbol == "KNN_TPU_OBS_LOG"
               and "never read by source" in f.message
               for f in rep.findings)


def test_switch_checker_family_prefix_consumption(tmp_path):
    """A family's members count as consumed through the family prefix
    appearing as a code literal (admission.py reads its whole family
    wholesale) — but the RESERVED root namespaces never consume
    anything, or the invariant would be vacuous."""
    members = [s.name for s in sw.SWITCHES
               if s.name.startswith("KNN_TPU_ADMISSION_")
               and not s.family]
    assert members, "catalog lost its admission rows?"
    kept = "\n".join(n for n in ALL_SWITCH_NAMES.splitlines()
                     if not n.startswith("KNN_TPU_ADMISSION_"))
    tree = dict(GOOD_SWITCH_TREE)
    # members consumed only via the non-reserved family prefix: green
    tree["knn_tpu/mod.py"] = (
        f'_READS = """\n{kept}\n"""\n'
        f'ENV_PREFIX = "KNN_TPU_ADMISSION_"\n')
    write_tree(tmp_path, tree)
    rep = run_on(tmp_path, "switch-lockstep")
    assert rep.ok, [f.message for f in rep.findings]
    # the reserved KNN_TPU_ root prefix (always in code via the flight
    # recorder) must NOT stand in for the members
    tree["knn_tpu/mod.py"] = (
        f'_READS = """\n{kept}\n"""\n_ROOT = "KNN_TPU_"\n')
    write_tree(tmp_path, tree)
    rep2 = run_on(tmp_path, "switch-lockstep")
    flagged = {f.symbol for f in rep2.findings
               if "never read by source" in f.message}
    assert set(members) <= flagged


def test_lookup_family_semantics():
    assert sw.lookup("KNN_TPU_OBS") is not None
    assert sw.lookup("KNN_TPU_ADMISSION_") is not None  # declared prefix
    # a concrete member of a family still needs its own catalog row
    assert sw.lookup("KNN_TPU_ADMISSION_NOPE") is None
    assert sw.lookup("KNN_TPU_TOTALLY_BOGUS") is None


# --- metric-lockstep ----------------------------------------------------
def test_metric_checker_passes_known_good_tree(tmp_path):
    write_tree(tmp_path, {"knn_tpu/mod.py": '''
        NAME = "knn_tpu_serving_requests_total"
        SUFFIXED = "knn_tpu_serving_requests_total_count"  # prom summary
        '''})
    rep = run_on(tmp_path, "metric-lockstep")
    assert rep.ok, [f.message for f in rep.findings]


def test_metric_checker_flags_uncataloged_literal(tmp_path):
    write_tree(tmp_path, {"knn_tpu/mod.py": '''
        NAME = "knn_tpu_bogus_metric_total"
        '''})
    rep = run_on(tmp_path, "metric-lockstep")
    assert not rep.ok
    assert any(f.symbol == "knn_tpu_bogus_metric_total"
               for f in rep.findings)


def test_metric_shim_same_exit_codes():
    """scripts/lint_metric_names.py is a thin shim over the framework
    checker: exit 0 on the green tree (the historical contract the
    check_tier1 wiring relies on)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint_metric_names.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# --- locked-mutation ----------------------------------------------------
BAD_CLASS = '''
    import threading


    class Box:
        """A shared box.

        Thread-safety: guarded by ``self._lock``.
        """

        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._items = {}

        def bad(self):
            self._count = 1
            self._count += 1
            self._items["k"] = 2

        def good(self):
            with self._lock:
                self._count = 3
                self._items["k"] = 4

        def helper(self):
            """Bump the count.  Caller holds ``self._lock``."""
            self._count += 1
    '''


def test_concurrency_checker_flags_unlocked_writes(tmp_path):
    write_tree(tmp_path, {"knn_tpu/box.py": BAD_CLASS})
    rep = run_on(tmp_path, "locked-mutation")
    assert not rep.ok
    syms = [f.symbol for f in rep.findings]
    assert syms.count("Box.bad") == 3  # assign, augassign, subscript
    # locked writes and Caller-holds helpers are clean
    assert all(s == "Box.bad" for s in syms)


def test_concurrency_checker_passes_locked_class(tmp_path):
    good = BAD_CLASS.replace('''
        def bad(self):
            self._count = 1
            self._count += 1
            self._items["k"] = 2
''', "")
    write_tree(tmp_path, {"knn_tpu/box.py": good})
    rep = run_on(tmp_path, "locked-mutation")
    assert rep.ok, [f.message for f in rep.findings]


def test_concurrency_checker_flags_nested_callback_write(tmp_path):
    """A nested def's body runs when CALLED, not where it is defined:
    a callback built under the lock (fut.add_done_callback) executes
    later on another thread with no lock held, so the enclosing
    ``with self._lock:`` must not cover its writes."""
    write_tree(tmp_path, {"knn_tpu/cb.py": '''
        import threading


        class Box:
            """Thread-safety: guarded by ``self._lock``."""

            def __init__(self):
                self._lock = threading.Lock()
                self._done = 0

            def submit(self, fut):
                with self._lock:
                    def _cb(_fut):
                        self._done += 1
                    fut.add_done_callback(_cb)

            def locked_nested(self, fut):
                def _cb(_fut):
                    with self._lock:
                        self._done += 1  # takes the lock itself: clean
                fut.add_done_callback(_cb)
        '''})
    rep = run_on(tmp_path, "locked-mutation")
    assert not rep.ok
    syms = [f.symbol for f in rep.findings]
    assert syms == ["Box.submit"]


def test_concurrency_checker_flags_other_store_contexts(tmp_path):
    """`for self._x in ...:` and `with ... as self._x:` rebind shared
    attributes exactly like assignments and must be flagged outside
    the lock — and stay clean inside it."""
    write_tree(tmp_path, {"knn_tpu/stores.py": '''
        import threading


        class Box:
            """Thread-safety: guarded by ``self._lock``."""

            def __init__(self):
                self._lock = threading.Lock()
                self._cursor = 0
                self._fh = None

            def bad_loop(self, chunks):
                for self._cursor in chunks:
                    pass

            def bad_with(self, path):
                with open(path) as self._fh:
                    pass

            def good_loop(self, chunks):
                with self._lock:
                    for self._cursor in chunks:
                        pass
        '''})
    rep = run_on(tmp_path, "locked-mutation")
    assert not rep.ok
    syms = sorted(f.symbol for f in rep.findings)
    assert syms == ["Box.bad_loop", "Box.bad_with"]


def test_concurrency_checker_flags_marker_guarding_nothing(tmp_path):
    write_tree(tmp_path, {"knn_tpu/empty.py": '''
        import threading


        class Empty:
            """Thread-safety: guarded by ``self._lock``."""

            def method(self):
                return 1
        '''})
    rep = run_on(tmp_path, "locked-mutation")
    assert any("guards nothing" in f.message or "no shared attributes"
               in f.message for f in rep.findings)


def test_annotated_runtime_classes_lint_clean():
    """The five thread-safe classes the suite annotates (registry
    instruments, QueryQueue, ServingEngine, SLOEngine, PhaseTimer) pass
    the checker on the real tree — with only the justified single-writer
    suppression (queue completer's service-rate state)."""
    rep = analysis.run(REPO, names=["locked-mutation"])
    assert rep.ok, [f.message for f in rep.findings]
    assert rep.suppressed == 1
    text = open(os.path.join(REPO, "knn_tpu", "serving", "queue.py"),
                encoding="utf-8").read()
    assert "Thread-safety: guarded by ``self._cond``" in text


# --- jax-hygiene --------------------------------------------------------
def test_jax_checker_flags_wall_clock_in_library_only(tmp_path):
    write_tree(tmp_path, {
        "knn_tpu/mod.py": '''
            import time

            def f():
                return time.time()

            def g():
                return time.perf_counter()
            ''',
        "scripts/driver.py": '''
            import time

            STARTED = time.time()  # session drivers are out of scope
            ''',
    })
    rep = run_on(tmp_path, "jax-hygiene")
    assert len(rep.findings) == 1
    assert rep.findings[0].path == os.path.join("knn_tpu", "mod.py")


def test_jax_checker_hot_path_and_allow(tmp_path):
    write_tree(tmp_path, {"knn_tpu/hot.py": '''
        import numpy as np

        from knn_tpu.analysis.annotations import hot_path


        @hot_path
        def dispatch(x):
            y = np.asarray(x)          # finding: host sync on hot path
            x.block_until_ready()      # finding
            return y


        @hot_path(allow=("np.asarray",))
        def coerce(x):
            return np.asarray(x)       # whitelisted at the annotation


        def cold(x):
            return np.asarray(x)       # unannotated: out of scope
        '''})
    rep = run_on(tmp_path, "jax-hygiene")
    syms = sorted(f.symbol for f in rep.findings)
    assert syms == ["dispatch", "dispatch"]


def test_jax_checker_static_arg_hygiene(tmp_path):
    write_tree(tmp_path, {"knn_tpu/jit.py": '''
        from functools import partial

        import jax


        @partial(jax.jit, static_argnames=("shape",))
        def build(x, shape=[8, 8]):
            return x


        def caller(x):
            return build(x, shape=[16, 16])
        '''})
    rep = run_on(tmp_path, "jax-hygiene")
    msgs = [f.message for f in rep.findings]
    assert any("unhashable default" in m for m in msgs)
    assert any("unhashable list" in m for m in msgs)


# --- vmem model: mirror pins against the source modules -----------------
def test_vmem_geometry_mirrors_pallas_kernel():
    from knn_tpu.ops import pallas_knn as pk

    assert vmem.TILE_N_DEFAULT == pk.TILE_N
    assert vmem.BLOCK_Q_DEFAULT == pk.BLOCK_Q
    assert vmem.BIN_W == pk.BIN_W
    assert vmem.DIM_CHUNK == pk.DIM_CHUNK
    assert vmem.MAX_CARRY_DEPTH == pk.MAX_CARRY_DEPTH


def test_vmem_operand_widths_mirror_roofline():
    from knn_tpu.obs import roofline

    assert set(vmem.DB_PARTS) == set(roofline.DB_ELEM_BYTES)
    for prec, (n_parts, chunk_w, elem_b) in vmem.DB_PARTS.items():
        per_dim = n_parts * chunk_w * elem_b / vmem.DIM_CHUNK
        assert per_dim == roofline.DB_ELEM_BYTES[prec], prec
    assert vmem.AUX_ROWS == roofline.AUX_ROWS
    assert vmem.AUX_ROWS_DEFAULT == roofline.AUX_ROWS_DEFAULT


def test_operand_width_tables_are_the_shared_widths_objects():
    """Identity pin: every consumer re-exports the ONE width table in
    knn_tpu.analysis.widths — the SAME objects, not copies.  An `is`
    here (vs `==`) rules out the drift mode where a consumer forks its
    table, passes today's equality, and then diverges on the next new
    precision arm."""
    from knn_tpu.analysis import hbm, widths
    from knn_tpu.obs import roofline

    assert roofline.DB_ELEM_BYTES is widths.DB_ELEM_BYTES
    assert roofline.AUX_ROWS is widths.AUX_ROWS
    assert roofline.QUERY_ELEM_BYTES is widths.QUERY_ELEM_BYTES
    assert vmem.DB_PARTS is widths.DB_PARTS
    assert vmem.AUX_ROWS is widths.AUX_ROWS
    assert vmem.DIM_CHUNK == widths.DIM_CHUNK == roofline.DIM_CHUNK
    # ints are compared by value (an int re-export has no alias risk)
    assert hbm.AUX_BYTES_PER_ROW == widths.AUX_BYTES_PER_ROW


def test_launch_estimate_breakdown_and_monotonicity():
    shape = dict(vmem.HEADLINE_SHAPE)
    est = vmem.launch_estimate(**shape)
    assert est["total_bytes"] == sum(est["breakdown"].values())
    small = vmem.launch_estimate(**shape, tile_n=8192)["total_bytes"]
    big = vmem.launch_estimate(**shape, tile_n=32768)["total_bytes"]
    assert small < big
    bq = vmem.launch_estimate(**shape, block_q=512)["total_bytes"]
    assert est["total_bytes"] < bq
    with pytest.raises(ValueError):
        vmem.launch_estimate(**shape, precision="float8")
    with pytest.raises(ValueError):
        vmem.launch_estimate(**shape, kernel="warp")


def test_budget_for_provenance():
    assert vmem.budget_for("TPU v5e") == (128 * vmem.MIB, False)
    assert vmem.budget_for("TPU v3") == (16 * vmem.MIB, False)
    # unknown TPU generations get the modern default, flagged estimated
    assert vmem.budget_for("TPU v9x") == (vmem.DEFAULT_VMEM_BYTES, True)
    # no VMEM to budget on host backends: N/A, never a refusal
    assert vmem.budget_for(None, "cpu") == (None, False)
    assert vmem.budget_for("cpu") == (None, False)


def test_check_candidate_verdicts():
    shape = dict(vmem.HEADLINE_SHAPE)
    ok = vmem.check_candidate({}, **shape, device_kind="TPU v5e")
    assert ok["checked"] and ok["fits"] is True
    over = vmem.check_candidate({"kernel": "streaming", "block_q": 4096},
                                **shape, device_kind="TPU v3")
    assert over["fits"] is False
    assert over["estimate_bytes"] > over["budget_bytes"]
    na = vmem.check_candidate({}, **shape, backend="cpu")
    assert na["checked"] is False and na["fits"] is None


def test_default_knobs_fit_target_device():
    from knn_tpu.tuning.autotune import DEFAULT_KNOBS

    verdict = vmem.check_candidate(
        DEFAULT_KNOBS, **vmem.HEADLINE_SHAPE,
        device_kind=vmem.TARGET_DEVICE_KIND)
    assert verdict["fits"] is True


def test_knob_grid_carries_no_unfittable_candidate():
    """The enumeration bound: every grid candidate fits at least one
    known device kind's VMEM at the headline shape (the same invariant
    the vmem-budget checker enforces statically)."""
    from knn_tpu import tuning

    for level in ("quick", "standard", "full"):
        for cand in tuning.knob_grid(level):
            knobs = {**tuning.DEFAULT_KNOBS, **cand}
            assert vmem.fits_some_kind(knobs, **vmem.HEADLINE_SHAPE), (
                level, cand)


def test_vmem_checker_flags_seeded_over_budget_candidate():
    """The known-bad fixture: a grid carrying a fits-nowhere candidate
    must produce a vmem-budget finding (and would flip cli lint red)."""
    from knn_tpu.tuning.autotune import DEFAULT_KNOBS

    bad = {"kernel": "streaming", "precision": "bf16x3f",
           "tile_n": 32768}
    findings = grid_findings([bad], DEFAULT_KNOBS)
    assert findings and findings[0].checker == "vmem-budget"
    assert "over EVERY known device kind" in findings[0].message
    # the clean grid produces none
    assert grid_findings([{}], DEFAULT_KNOBS) == []


def test_vmem_checker_red_when_grid_regresses(tmp_path, monkeypatch):
    """Seeded regression, checker level: an over-VMEM candidate smuggled
    into knob_grid flips the vmem-budget checker (hence cli lint)
    nonzero."""
    import importlib

    at = importlib.import_module("knn_tpu.tuning.autotune")

    real = at.knob_grid

    def rigged(level="standard"):
        out = real(level)
        out.append({**at.DEFAULT_KNOBS, "kernel": "streaming",
                    "precision": "bf16x3f", "tile_n": 32768})
        return out

    monkeypatch.setattr(at, "knob_grid", rigged)
    rep = analysis.run(REPO, names=["vmem-budget"])
    assert not rep.ok
    assert any(f.checker == "vmem-budget" for f in rep.findings)


def test_vmem_checker_green_on_repo():
    rep = analysis.run(REPO, names=["vmem-budget"])
    assert rep.ok, [f.message for f in rep.findings]


# --- the autotuner's runtime VMEM gate ----------------------------------
@pytest.fixture
def tune_data():
    rng = np.random.default_rng(7)
    db = (rng.random((700, 16)) * 64).astype(np.float32)
    q = (rng.random((8, 16)) * 64).astype(np.float32)
    return db, q


def test_autotune_refuses_over_budget_candidate_before_timing(
        tune_data, tmp_path):
    """An over-VMEM candidate is refused with provenance BEFORE the
    bitwise gate or any timing — it can never win, and the refusal is
    recorded like roofline pruning."""
    from knn_tpu import tuning

    db, q = tune_data
    tuning.reset_counters()
    entry = tuning.autotune(
        db, q, 5, margin=4, runs=1,
        cache_path=str(tmp_path / "t.json"),
        grid=[{}, {"kernel": "streaming", "block_q": 4096}],
        device_kind="TPU v2")  # 16 MiB budget: bq4096 cannot fit
    label = "block_q=4096,kernel=streaming"
    assert entry["timings_ms"][label] is None
    assert entry["errors"][label].startswith("vmem-refused:")
    assert entry["winner"] == "defaults"
    assert entry["vmem"]["device_kind"] == "TPU v2"
    assert entry["vmem"]["candidates_refused"] == 1
    assert label in entry["vmem"]["refused"]
    refused = entry["vmem"]["refused"][label]
    assert refused["estimate_bytes"] > refused["budget_bytes"]
    counters = tuning.counters()
    assert counters["candidates_vmem_refused"] == 1
    assert counters["candidates_timed"] == 1  # only the defaults


def test_autotune_vmem_gate_disarms_off_tpu(tune_data, tmp_path):
    """cpu/interpret backends have no VMEM: no refusals, no vmem block
    — the pre-gate entry shape is unchanged."""
    from knn_tpu import tuning

    db, q = tune_data
    tuning.reset_counters()
    entry = tuning.autotune(
        db, q, 5, margin=4, runs=1,
        cache_path=str(tmp_path / "t.json"), grid=[{}])
    assert "vmem" not in entry
    assert tuning.counters()["candidates_vmem_refused"] == 0


# --- lock-order harness (runtime deadlock detection) --------------------
def test_lockorder_detects_inversion():
    rec = LockOrderRecorder()
    a = InstrumentedLock("A", rec)
    b = InstrumentedLock("B", rec)
    t1_done = threading.Event()

    def t1():
        # A -> B ...
        with a:
            with b:
                pass
        t1_done.set()

    def t2():
        # ... and B -> A in another thread: an order inversion.  Run
        # strictly after t1 so the locks themselves can never deadlock
        # — the ORDER graph still has the cycle, which is the point:
        # the harness convicts the interleaving that got lucky.
        t1_done.wait(5)
        with b:
            with a:
                pass

    ts = [threading.Thread(target=t1), threading.Thread(target=t2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    cyc = rec.find_cycle()
    assert cyc is not None and cyc[0] == cyc[-1]
    with pytest.raises(AssertionError, match="lock-order cycle"):
        rec.assert_acyclic()


def test_lockorder_consistent_order_is_acyclic():
    rec = LockOrderRecorder()
    a = InstrumentedLock("A", rec)
    b = InstrumentedLock("B", rec)

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert rec.order_graph()["A"] == {"B"}
    assert rec.find_cycle() is None
    rec.assert_acyclic()


def test_instrument_swaps_lock_attrs():
    rec = LockOrderRecorder()

    class HasLock:
        def __init__(self):
            self._lock = threading.Lock()

    class HasNeither:
        pass

    obj = HasLock()
    instrument(rec, thing=obj)
    assert isinstance(obj._lock, InstrumentedLock)
    with pytest.raises(ValueError, match="neither _lock nor _cond"):
        instrument(rec, bad=HasNeither())


def test_serving_stack_lock_order_acyclic_under_hammer(rng):
    """The 8-thread hammer over the REAL thread-safe classes (engine,
    queue, SLO engine, registry, a registry histogram) with every lock
    instrumented: the recorded acquisition-order graph must be acyclic —
    a cycle is a deadlock waiting for its interleaving even when this
    run got lucky."""
    from knn_tpu import obs
    from knn_tpu.obs import names as mn
    from knn_tpu.obs.slo import SLOEngine
    from knn_tpu.parallel import ShardedKNN, make_mesh
    from knn_tpu.serving import QueryQueue, ServingEngine

    db = (rng.random((64, 8)) * 32).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(), k=3)
    engine = ServingEngine(prog, buckets=(8, 16))
    engine.warmup()
    slo_engine = SLOEngine()
    rec = LockOrderRecorder()
    hist = obs.histogram(mn.SERVING_REQUEST_LATENCY, op="search")
    with QueryQueue(engine, max_wait_ms=1.0) as queue:
        instrument(rec, engine=engine, queue=queue, slo=slo_engine,
                   registry=obs.get_registry(), latency_hist=hist)
        barrier = threading.Barrier(8)
        errors = []

        def hammer(i):
            try:
                barrier.wait(10)
                futs = [queue.submit(db[: 1 + (i + j) % 8])
                        for j in range(4)]
                queue.stats()
                slo_engine.evaluate()
                for f in futs:
                    f.result(timeout=30)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        ts = [threading.Thread(target=hammer, args=(i,))
              for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors
    assert rec.edges(), "hammer recorded no lock interleavings"
    rec.assert_acyclic()


# --- cli lint subprocess contract ---------------------------------------
@pytest.mark.slow
def test_cli_lint_green_on_repo_json():
    proc = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "lint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert set(payload["checkers"]) == set(CHECKERS)
    assert payload["suppressed"] >= 1  # justified baseline, never hidden


@pytest.mark.slow
def test_cli_lint_seeded_regression_exits_nonzero(tmp_path):
    """An uncataloged switch in a lint root flips cli lint to exit 1
    with the finding in the JSON report."""
    write_tree(tmp_path, {"knn_tpu/rogue.py": '''
        import os

        FLAG = os.environ.get("KNN_TPU_TOTALLY_BOGUS")
        '''})
    proc = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "lint", "--json",
         "--root", str(tmp_path), "--checker", "switch-lockstep"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert any(f["symbol"] == "KNN_TPU_TOTALLY_BOGUS"
               for f in payload["findings"])


def test_full_suite_green_in_process():
    """The in-process twin of the subprocess gate: every checker over
    the real tree, zero findings, every suppression used and
    justified."""
    rep = analysis.run(REPO)
    assert rep.ok, rep.render_text()
    assert rep.suppressed >= 1
