"""bench.py contract tests — round 1 died because the bench crashed in
backend init and emitted nothing parseable.  These pin the contract: one
JSON line on stdout, success or failure, with the documented fields."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout=300):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    return proc.returncode, lines


@pytest.mark.slow
def test_bench_emits_one_parseable_success_line():
    rc, lines = _run({
        "KNN_BENCH_PLATFORM": "cpu",
        "KNN_BENCH_N": "4000", "KNN_BENCH_NQ": "64", "KNN_BENCH_BATCH": "32",
        "KNN_BENCH_K": "5", "KNN_BENCH_MARGIN": "4", "KNN_BENCH_TILE": "2048",
        "KNN_BENCH_CPU_QUERIES": "8", "KNN_BENCH_RUNS": "1",
        "KNN_BENCH_MODES": "certified_approx",
    })
    assert rc == 0, lines
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for field in ("metric", "value", "unit", "vs_baseline", "runs",
                  "selectors", "mode", "backend"):
        assert field in rec, field
    assert rec["value"] > 0
    assert rec["unit"] == "queries/s"
    sel = rec["selectors"]["certified_approx"]
    assert sel["certified_stats"]["certified"] + \
        sel["certified_stats"]["fallback_queries"] == 64
    # VERDICT r4 item 6: EVERY selector carries its own device-phase
    # rate, at the sweep's batch shape
    pb = sel["phase_breakdown"]
    assert pb["device_batch"] == 32 and pb["device_qps"] > 0
    # the line is self-reproducing: the grid-order knob is part of the
    # recorded pallas geometry
    assert rec["pallas_knobs"]["grid_order"] == "query_major"
    # roofline attribution beside mfu on the selector entry AND the
    # line top-level; a CPU run models against the generic fallback
    # peaks and says so (roofline_estimated)
    assert sel["roofline"]["bound_class"] in (
        "hbm_bound", "mxu_bound", "vpu_select_bound")
    assert sel["roofline"]["roofline_pct"] is not None
    assert rec["roofline"]["ceiling_qps"] > 0
    assert rec["roofline_pct"] == rec["roofline"]["roofline_pct"]
    assert rec["roofline_estimated"] is True
    assert rec["roofline"]["estimated"] is True


def test_bench_bad_config_still_emits_json_line():
    rc, lines = _run({"KNN_BENCH_CONFIG": "not_a_config"}, timeout=60)
    assert rc == 1
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert "error" in rec


def test_bench_bad_platform_still_emits_json_line():
    rc, lines = _run({
        "KNN_BENCH_PLATFORM": "bogus",
        "KNN_BENCH_INIT_ATTEMPTS": "1",
        "KNN_BENCH_INIT_TIMEOUT": "30",
        "KNN_BENCH_FALLBACK_CPU": "0",  # default-on fallback would succeed
    }, timeout=120)
    assert rc == 1
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert "backend_init" in rec["error"]


@pytest.mark.slow
def test_bench_falls_back_to_cpu_by_default():
    # the round-3 lesson: a flagged CPU number beats a null round record.
    # A bogus accelerator platform + the default-on fallback must yield a
    # real measurement honestly stamped backend=cpu.
    rc, lines = _run({
        "KNN_BENCH_PLATFORM": "bogus",
        "KNN_BENCH_INIT_ATTEMPTS": "1",
        "KNN_BENCH_INIT_TIMEOUT": "30",
        "KNN_BENCH_N": "4000", "KNN_BENCH_NQ": "32", "KNN_BENCH_BATCH": "32",
        "KNN_BENCH_K": "5", "KNN_BENCH_MARGIN": "4", "KNN_BENCH_TILE": "2048",
        "KNN_BENCH_CPU_QUERIES": "8", "KNN_BENCH_RUNS": "1",
        "KNN_BENCH_MODES": "exact",
    })
    assert rc == 0, lines
    assert len(lines) == 1, lines  # the one-JSON-line stdout contract
    rec = json.loads(lines[0])
    assert rec["value"] > 0
    assert rec["backend"] == "cpu"


def test_probe_hang_is_killed_and_reported(monkeypatch, tmp_path):
    """The round-3 failure mode: backend init hangs forever.  The probe
    child must be KILLED at the timeout (parent lock untouched) and the
    hang reported distinctly from a fast failure."""
    import importlib
    import bench as bench_mod

    bench = importlib.reload(bench_mod)
    # a child that sleeps forever stands in for the stale-claim hang
    hang = tmp_path / "hang.py"
    hang.write_text("import time\ntime.sleep(3600)\n")
    real_exe = sys.executable
    real_run = subprocess.run

    def fake_run(cmd, **kw):
        # substitute the hanging child for the probe's -c payload
        return real_run([real_exe, str(hang)], **{
            k: v for k, v in kw.items() if k != "env"})

    monkeypatch.setattr(subprocess, "run", fake_run)
    import time as _time

    t0 = _time.monotonic()
    ok, err, hung = bench._probe_backend_subprocess(timeout=2)
    took = _time.monotonic() - t0
    assert not ok and hung
    assert "hung" in err
    assert took < 30  # the child was killed at the timeout, not awaited


def test_probe_fast_failure_not_flagged_as_hang(monkeypatch, tmp_path):
    import importlib
    import bench as bench_mod

    bench = importlib.reload(bench_mod)
    boom = tmp_path / "boom.py"
    boom.write_text("raise SystemExit('no accelerator')\n")
    real_run = subprocess.run

    def fake_run(cmd, **kw):
        return real_run([sys.executable, str(boom)], **{
            k: v for k, v in kw.items() if k != "env"})

    monkeypatch.setattr(subprocess, "run", fake_run)
    ok, err, hung = bench._probe_backend_subprocess(timeout=30)
    assert not ok and not hung
    assert "rc=" in err


@pytest.mark.slow
def test_bench_glove_cosine_runs_certified_library_path():
    # VERDICT r3 item 4: the cosine config must run the certified
    # machinery through the LIBRARY (ShardedKNN normalizes at placement),
    # not a harness-side normalize-and-relabel trick.  Tiny-shape glove
    # on CPU: all three modes must report recall 1.0 vs the raw-cosine
    # native oracle.
    rc, lines = _run({
        "KNN_BENCH_PLATFORM": "cpu",
        "KNN_BENCH_CONFIG": "glove",
        "KNN_BENCH_N": "3000", "KNN_BENCH_NQ": "48", "KNN_BENCH_BATCH": "24",
        "KNN_BENCH_K": "7", "KNN_BENCH_MARGIN": "6", "KNN_BENCH_TILE": "1024",
        "KNN_BENCH_CPU_QUERIES": "8", "KNN_BENCH_RUNS": "1",
        "KNN_BENCH_DIM": "24", "KNN_BENCH_CPU_CACHE": "0",
    })
    assert rc == 0, lines
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["value"] > 0
    assert rec["metric_fn"].startswith("cosine")
    sels = rec["selectors"]
    assert set(sels) == {"exact", "certified_approx", "certified_pallas",
                         "serving"}
    for name, sel in sels.items():
        if name == "serving":
            # trace replay, not a recall-gated sweep: sustained rate +
            # tail latency + the compile bound instead of recall_at_k
            assert sel["sustained_qps"] > 0, sel
            assert {"p50", "p95", "p99"} <= set(sel["latency_ms"]), sel
            assert sel["compile_count"] <= len(sel["bucket_ladder"]), sel
            continue
        assert sel.get("recall_at_k") == 1.0, (name, sel)
    # the traffic numbers are hoisted to the top level of the JSON line
    assert rec["serving_sustained_qps"] > 0
    assert rec["serving_latency_ms"]["p99"] >= rec["serving_latency_ms"]["p50"]
