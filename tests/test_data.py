"""L1 data layer tests: reference CSV format round-trips, vecs formats,
and malformed-input rejection (the reference silently corrupts instead,
knn_mpi.cpp:169-170)."""

import numpy as np
import pytest

from knn_tpu.data import (
    make_blobs,
    read_bvecs,
    read_fvecs,
    read_ivecs,
    read_labeled_csv,
    read_unlabeled_csv,
    save_labeled_csv,
    save_unlabeled_csv,
    write_fvecs,
    write_ivecs,
    write_labels,
)
from knn_tpu.data.csv_io import read_labels


def test_labeled_csv_roundtrip(tmp_path, rng):
    feats = rng.normal(size=(20, 7)).astype(np.float32)
    labels = rng.integers(0, 4, size=20).astype(np.int32)
    p = str(tmp_path / "train.csv")
    save_labeled_csv(p, feats, labels)
    f2, l2 = read_labeled_csv(p, dim=7)
    np.testing.assert_array_equal(l2, labels)
    np.testing.assert_allclose(f2, feats, rtol=1e-6)


def test_unlabeled_csv_roundtrip(tmp_path, rng):
    feats = rng.normal(size=(11, 3)).astype(np.float32)
    p = str(tmp_path / "test.csv")
    save_unlabeled_csv(p, feats)
    np.testing.assert_allclose(read_unlabeled_csv(p, dim=3), feats, rtol=1e-6)


def test_labels_roundtrip(tmp_path):
    labels = np.asarray([3, 1, 4, 1, 5], dtype=np.int32)
    p = str(tmp_path / "Test_label.csv")
    write_labels(p, labels)
    np.testing.assert_array_equal(read_labels(p), labels)
    # format check: one integer per line, like knn_mpi.cpp:385-393 writes
    assert open(p).read() == "3\n1\n4\n1\n5\n"


def test_ragged_csv_rejected(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1,2.0,3.0\n2,4.0\n")
    # message differs between the python fallback and the native fast path
    with pytest.raises(ValueError, match="expected 3 fields|ragged"):
        read_labeled_csv(str(p))


def test_wrong_dim_rejected(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("1,2.0,3.0\n")
    with pytest.raises(ValueError, match="columns"):
        read_labeled_csv(str(p), dim=5)


def test_non_integer_labels_rejected(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("1.5,2.0,3.0\n")
    with pytest.raises(ValueError, match="non-integer"):
        read_labeled_csv(str(p))


def test_empty_csv_rejected(tmp_path):
    p = tmp_path / "e.csv"
    p.write_text("\n\n")
    with pytest.raises(ValueError, match="empty"):
        read_unlabeled_csv(str(p))


def test_fvecs_roundtrip(tmp_path, rng):
    x = rng.normal(size=(9, 16)).astype(np.float32)
    p = str(tmp_path / "a.fvecs")
    write_fvecs(p, x)
    np.testing.assert_array_equal(read_fvecs(p), x)


def test_ivecs_roundtrip(tmp_path, rng):
    x = rng.integers(0, 1000, size=(5, 100)).astype(np.int32)
    p = str(tmp_path / "a.ivecs")
    write_ivecs(p, x)
    np.testing.assert_array_equal(read_ivecs(p), x)


def test_bvecs_read(tmp_path, rng):
    x = rng.integers(0, 256, size=(4, 8)).astype(np.uint8)
    n, dim = x.shape
    rows = np.concatenate(
        [np.full((n, 1), dim, np.int32).view(np.uint8).reshape(n, 4), x], axis=1
    )
    p = str(tmp_path / "a.bvecs")
    rows.tofile(p)
    np.testing.assert_array_equal(read_bvecs(p), x)


def test_truncated_vecs_rejected(tmp_path, rng):
    x = rng.normal(size=(3, 8)).astype(np.float32)
    p = str(tmp_path / "a.fvecs")
    write_fvecs(p, x)
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-3])
    with pytest.raises(ValueError, match="not a multiple"):
        read_fvecs(p)


def test_make_blobs_separable():
    feats, labels = make_blobs(300, 8, 3, cluster_std=0.2, seed=1)
    assert feats.shape == (300, 8) and labels.shape == (300,)
    assert set(np.unique(labels)) == {0, 1, 2}
    # tight, well-separated blobs: class centroids far apart vs spread
    cents = np.stack([feats[labels == c].mean(0) for c in range(3)])
    d01 = np.linalg.norm(cents[0] - cents[1])
    assert d01 > 1.0


def test_make_mnist_like_shapes_and_accuracy_band():
    # small-scale draw from the MNIST-shaped surrogate: pixel range, label
    # range, and a KNN accuracy inside the band the generator is calibrated
    # for (the reference's oracle reports 95.39%, PDF p.12)
    from knn_tpu.data.datasets import make_mnist_like

    train, trl, test, tel, val, vall = make_mnist_like(4000, 500, 500, seed=3)
    assert train.shape == (4000, 784) and test.shape == (500, 784)
    for arr in (train, test, val):
        assert arr.dtype == np.float32
        assert arr.min() >= 0.0 and arr.max() <= 255.0
    for lab in (trl, tel, vall):
        assert lab.dtype == np.int32
        assert lab.min() >= 0 and lab.max() <= 9
    # normalized K=50 L2 KNN accuracy (numpy, no jax needed)
    lo, hi = train.min(0), train.max(0)
    rng_ = np.where(hi - lo != 0, hi - lo, 1)
    trn, ten = (train - lo) / rng_, (test - lo) / rng_
    d = (ten**2).sum(1)[:, None] + (trn**2).sum(1)[None, :] - 2 * ten @ trn.T
    idx = np.argpartition(d, 50, axis=1)[:, :50]
    pred = np.array([np.bincount(trl[i], minlength=10).argmax() for i in idx])
    acc = (pred == tel).mean()
    assert 0.88 <= acc <= 0.995, acc


def test_bvecs_quantized_loader_is_byte_exact(tmp_path, rng):
    # bvecs payload -> int8 coarse-pass feed: unit scales, -128 shift,
    # dequantization reproduces the bytes exactly (no f32 round trip)
    from knn_tpu.data.vecs import read_bvecs_quantized
    from knn_tpu.ops.quantize import dequantize

    x = rng.integers(0, 256, size=(13, 9), dtype=np.uint8)
    n, dim = x.shape
    rows = np.concatenate(
        [np.full((n, 1), dim, np.int32).view(np.uint8).reshape(n, 4), x],
        axis=1)
    p = str(tmp_path / "q.bvecs")
    rows.tofile(p)
    qr = read_bvecs_quantized(p)
    assert qr.values.dtype == np.int8
    assert qr.offset == 128.0
    np.testing.assert_array_equal(qr.scales, np.ones(13, np.float32))
    np.testing.assert_array_equal(
        qr.values.astype(np.int16), x.astype(np.int16) - 128)
    np.testing.assert_array_equal(dequantize(qr), x.astype(np.float32))
