"""Exact refinement tests: coarse candidates + float64 re-select must equal
the brute-force float64 oracle."""

import numpy as np
import pytest

from knn_tpu.ops.refine import refine_exact


def _oracle(db, queries, k, metric="l2"):
    q = queries.astype(np.float64)[:, None, :]
    c = db.astype(np.float64)[None, :, :]
    if metric == "l2":
        d = ((c - q) ** 2).sum(-1)
    elif metric == "l1":
        d = np.abs(c - q).sum(-1)
    else:
        raise ValueError(metric)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


def test_refine_recovers_exact_topk(rng):
    db = rng.normal(size=(500, 16)).astype(np.float32)
    queries = rng.normal(size=(20, 16)).astype(np.float32)
    ref_d, ref_i = _oracle(db, queries, 10)
    # coarse candidates: the true top-30 shuffled (any superset works)
    _, cand = _oracle(db, queries, 30)
    perm = rng.permutation(30)
    d, i = refine_exact(db, queries, cand[:, perm], 10)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-12)


def test_refine_handles_duplicates_and_sentinels(rng):
    db = rng.normal(size=(50, 4)).astype(np.float32)
    queries = rng.normal(size=(3, 4)).astype(np.float32)
    ref_d, ref_i = _oracle(db, queries, 5)
    _, cand = _oracle(db, queries, 8)
    cand = np.concatenate(
        [cand, cand[:, :2], np.full((3, 2), 1 << 30, dtype=np.int64)], axis=-1
    )
    d, i = refine_exact(db, queries, cand, 5)
    np.testing.assert_array_equal(i, ref_i)


def test_refine_l1_metric(rng):
    db = rng.normal(size=(200, 8)).astype(np.float32)
    queries = rng.normal(size=(7, 8)).astype(np.float32)
    ref_d, ref_i = _oracle(db, queries, 4, "l1")
    _, cand = _oracle(db, queries, 12, "l1")
    d, i = refine_exact(db, queries, cand, 4, metric="l1")
    np.testing.assert_array_equal(i, ref_i)


def test_refine_ties_break_to_lower_index(rng):
    db = rng.normal(size=(40, 4)).astype(np.float32)
    db[20:] = db[:20]  # exact duplicates: ties must go to the lower index
    queries = db[:5].copy()
    cand = np.tile(np.arange(40), (5, 1))
    _, i = refine_exact(db, queries, cand, 3)
    # nearest must be the query itself at its low index, not the duplicate
    np.testing.assert_array_equal(i[:, 0], np.arange(5))


def test_refine_rejects_too_few_candidates(rng):
    db = rng.normal(size=(10, 3)).astype(np.float32)
    q = rng.normal(size=(2, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="candidates"):
        refine_exact(db, q, np.zeros((2, 3), dtype=np.int64), 5)
