"""The unified telemetry subsystem (knn_tpu.obs): registry exactness and
thread-safety, disabled-mode no-op identity, exporter round-trips, span
propagation through micro-batch coalescing, and the ground-truth match
between scraped counters and independently counted serving/certified
activity — the acceptance surface of the obs ISSUE."""

import json
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.obs import names as mn

REPO = __file__.rsplit("/tests/", 1)[0]


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Every test starts from an empty ENABLED registry + event ring and
    leaves the env-driven state behind for the rest of the suite."""
    obs.reset(enabled=True)
    obs.reset_event_log(None)
    yield
    obs.reset()
    obs.reset_event_log(from_env=True)


# --- registry exactness -------------------------------------------------
def test_counter_gauge_histogram_exactness():
    c = obs.counter(mn.QUEUE_REQUESTS)
    c.inc()
    c.inc(4)
    assert c.get() == 5.0
    g = obs.gauge(mn.QUEUE_DEPTH_ROWS)
    g.set(10)
    g.inc(2)
    g.dec(5)
    assert g.get() == 7.0
    h = obs.histogram(mn.QUEUE_WAIT)
    for v in range(1, 101):
        h.observe(v / 100.0)
    s = h.summary()
    assert s["count"] == 100
    assert s["sum"] == pytest.approx(50.5)
    assert s["min"] == pytest.approx(0.01) and s["max"] == pytest.approx(1.0)
    assert s["p50"] == pytest.approx(0.505, abs=0.02)
    assert s["p99"] == pytest.approx(0.99, abs=0.02)


def test_histogram_window_is_bounded_but_lifetime_is_not():
    from knn_tpu.obs.registry import Histogram

    h = Histogram(window=16)
    h.observe_many(range(1000))
    s = h.summary()
    assert s["count"] == 1000  # lifetime
    assert s["window"] == 16  # bounded percentile window
    assert s["p50"] >= 983  # percentiles over the RECENT window


def test_labels_create_distinct_series_and_same_handle():
    a = obs.counter(mn.SERVING_REQUESTS, op="search")
    b = obs.counter(mn.SERVING_REQUESTS, op="predict")
    assert a is not b
    assert obs.counter(mn.SERVING_REQUESTS, op="search") is a
    a.inc(3)
    snap = obs.snapshot()[mn.SERVING_REQUESTS]
    by_op = {s["labels"]["op"]: s["value"] for s in snap["series"]}
    assert by_op == {"search": 3.0, "predict": 0.0}


def test_uncataloged_names_and_label_mismatches_refused():
    with pytest.raises(ValueError, match="not in the catalog"):
        obs.counter("knn_tpu_made_up_total")
    with pytest.raises(ValueError, match="is a counter"):
        obs.gauge(mn.QUEUE_REQUESTS)
    with pytest.raises(ValueError, match="takes labels"):
        obs.counter(mn.SERVING_REQUESTS)  # missing the op label
    with pytest.raises(ValueError):
        obs.counter(mn.QUEUE_REQUESTS, op="x")  # spurious label
    # the disabled registry validates identically (fail fast in dev)
    obs.reset(enabled=False)
    with pytest.raises(ValueError, match="not in the catalog"):
        obs.counter("knn_tpu_made_up_total")


def test_thread_hammer_counts_exact():
    c = obs.counter(mn.QUEUE_REQUESTS)
    h = obs.histogram(mn.QUEUE_WAIT)
    g = obs.gauge(mn.QUEUE_DEPTH_ROWS)
    n_threads, per = 8, 2000

    def work():
        for i in range(per):
            c.inc()
            h.observe(i)
            g.inc()

    ts = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.get() == n_threads * per
    assert h.summary()["count"] == n_threads * per
    assert g.get() == n_threads * per


# --- disabled mode ------------------------------------------------------
def test_disabled_mode_noop_identity():
    obs.reset(enabled=False)
    c = obs.counter(mn.QUEUE_REQUESTS)
    # ONE shared inert instrument across names/kinds/labels — no
    # allocation, no state, nothing exported
    assert c is obs.counter(mn.QUEUE_DISPATCHES)
    assert c is obs.gauge(mn.QUEUE_DEPTH_ROWS)
    assert c is obs.histogram(mn.QUEUE_WAIT)
    assert c is obs.NOOP
    c.inc()
    c.observe(3.0)
    assert c.get() == 0.0
    assert obs.snapshot() == {}
    assert obs.new_trace_id() is None
    with obs.span("serving.dispatch") as sp:
        sp.set("k", 1)
    assert sp.trace_id is None
    assert obs.get_event_log().recent() == []
    assert not obs.enabled()


def test_env_controls_default(monkeypatch):
    monkeypatch.setenv("KNN_TPU_OBS", "0")
    obs.reset()
    assert not obs.enabled()
    monkeypatch.delenv("KNN_TPU_OBS")
    obs.reset()
    assert obs.enabled()  # default-on


# --- exporters ----------------------------------------------------------
def test_prometheus_text_and_json_snapshot_round_trip(tmp_path):
    obs.counter(mn.SERVING_REQUESTS, op="search").inc(7)
    obs.gauge(mn.QUEUE_DEPTH_REQUESTS).set(3)
    obs.histogram(mn.QUEUE_WAIT).observe_many([0.1, 0.2, 0.3])
    text = obs.prometheus_text()
    assert '# TYPE knn_tpu_serving_requests_total counter' in text
    assert 'knn_tpu_serving_requests_total{op="search"} 7.0' in text
    assert 'knn_tpu_queue_depth_requests 3.0' in text
    assert '# TYPE knn_tpu_queue_wait_seconds summary' in text
    assert 'knn_tpu_queue_wait_seconds{quantile="0.5"} 0.2' in text
    assert 'knn_tpu_queue_wait_seconds_count 3' in text
    # JSON snapshot: atomic file -> identical Prometheus rendering
    path = tmp_path / "snap.json"
    obs.write_json_snapshot(str(path))
    payload = json.loads(path.read_text())
    assert payload["enabled"] is True
    assert obs.prometheus_text(payload["metrics"]) == text
    assert not list(tmp_path.glob("*.tmp"))  # no torn temp left behind


def test_http_metrics_endpoint():
    obs.counter(mn.QUEUE_REQUESTS).inc(11)
    server = obs.start_metrics_server(0)  # OS-assigned port
    try:
        port = server.server_address[1]
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "knn_tpu_queue_requests_total 11.0" in text
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10).read())
        assert js["metrics"][mn.QUEUE_REQUESTS]["series"][0]["value"] == 11.0
    finally:
        server.shutdown()


def test_metrics_cli_renders_snapshot(tmp_path):
    obs.counter(mn.QUEUE_REQUESTS).inc(5)
    path = tmp_path / "snap.json"
    obs.write_json_snapshot(str(path))
    r = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "metrics",
         "--snapshot", str(path), "--format", "prom"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "knn_tpu_queue_requests_total 5.0" in r.stdout


def test_jsonl_event_log_sink(tmp_path):
    path = tmp_path / "events.jsonl"
    obs.reset_event_log(str(path))
    tid = obs.new_trace_id()
    with obs.span("serving.dispatch", trace_id=tid, op="search", rows=4):
        pass
    with obs.span("serving.compile", op="search"):  # warmup-style: no id
        pass
    obs.emit_event("queue.dispatch", rows=4)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 3
    assert lines[0]["type"] == "span"
    assert lines[0]["span"] == "serving.dispatch"
    assert lines[0]["trace_id"] == tid and "ts" in lines[0]
    # ids are propagated, never minted inside span(): a span with no
    # request behind it must not fabricate a phantom trace
    assert "trace_id" not in lines[1]
    # every FILE line carries the process identity stamp (the fleet
    # aggregator's attribution key) — the in-memory ring does not
    assert all("identity" in ln for ln in lines)
    ident = lines[2].pop("identity")
    assert ident["process_index"] == 0 and "host" in ident
    assert ident["catalog_version"] == obs.names.catalog_version()
    assert lines[2] == {"ts": lines[2]["ts"], "type": "event",
                        "name": "queue.dispatch", "rows": 4}


# --- PhaseTimer (thin view over the registry) ---------------------------
def test_phase_timer_feeds_registry_and_rejects_nesting():
    from knn_tpu.utils.timing import PhaseTimer

    t = PhaseTimer()
    with t.phase("ingest"):
        pass
    with t.phase("ingest"):
        pass
    assert t.summary()["ingest"] >= 0.0
    h = obs.snapshot()[mn.PHASE_SECONDS]["series"]
    assert {"phase": "ingest"} in [s["labels"] for s in h]
    assert [s["value"]["count"] for s in h
            if s["labels"] == {"phase": "ingest"}] == [2]
    with pytest.raises(RuntimeError, match="nested"):
        with t.phase("outer"):
            with t.phase("inner"):
                pass
    # the failed nesting attempt must not wedge the timer
    with t.phase("after"):
        pass
    assert "after" in t.summary()


def test_phase_timer_concurrent_threads():
    from knn_tpu.utils.timing import PhaseTimer

    t = PhaseTimer()
    errs = []

    def work(name):
        try:
            for _ in range(200):
                with t.phase(name):
                    pass
        except Exception as e:  # pragma: no cover - the assertion surface
            errs.append(e)

    ts = [threading.Thread(target=work, args=(f"p{i}",)) for i in range(6)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert not errs
    s = t.summary()
    assert all(f"p{i}" in s for i in range(6))
    assert s["total"] >= max(s[f"p{i}"] for i in range(6)) - 1e-9


# --- serving ground truth (the acceptance criterion) --------------------
@pytest.fixture(scope="module")
def placed():
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN

    rng = np.random.default_rng(7)
    db = rng.standard_normal((256, 16)).astype(np.float32)
    return ShardedKNN(db, mesh=make_mesh(4, 2), k=5), rng


def test_serving_trace_prometheus_matches_ground_truth(placed):
    from knn_tpu.serving.buckets import bucket_for, split_sizes
    from knn_tpu.serving.engine import ServingEngine

    prog, rng = placed
    buckets = (8, 16, 32)
    eng = ServingEngine(prog, buckets=buckets)
    eng.warmup()
    sizes = [3, 8, 17, 1, 32, 9, 2, 2]
    reqs = [rng.standard_normal((s, 16)).astype(np.float32) for s in sizes]
    _, report = eng.replay(reqs, depth=2)

    # independent ground truth: the bucket each chunk of each request
    # must land in, recomputed here from the public ladder helpers
    expect = {}
    for s in sizes:
        for chunk in split_sizes(s, buckets[-1]):
            b = bucket_for(buckets, chunk)
            expect[b] = expect.get(b, 0) + 1

    text = obs.prometheus_text()
    assert (f'knn_tpu_serving_requests_total{{op="search"}} '
            f'{float(len(sizes))}') in text
    assert (f'knn_tpu_serving_queries_total{{op="search"}} '
            f'{float(sum(sizes))}') in text
    for b, n in expect.items():
        assert (f'knn_tpu_serving_dispatches_total'
                f'{{bucket="{b}",op="search"}} {float(n)}') in text
    # engine-side lifetime counters agree with the same ground truth
    assert report["requests_total"] == len(sizes)
    assert report["queries_total"] == sum(sizes)
    assert report["errors_total"] == 0
    # per-bucket registry counters == the engine's own tallies
    assert report["per_bucket_dispatches"] == expect
    # latency histogram recorded one sample per request
    lat = obs.snapshot()[mn.SERVING_REQUEST_LATENCY]["series"]
    assert [s["value"]["count"] for s in lat
            if s["labels"] == {"op": "search"}] == [len(sizes)]


def test_lifetime_counters_outlive_latency_window(placed):
    from knn_tpu.serving.engine import ServingEngine

    prog, rng = placed
    eng = ServingEngine(prog, buckets=(8,), latency_window=2)
    q = rng.standard_normal((3, 16)).astype(np.float32)
    for _ in range(5):
        eng.submit(q).result()
    st = eng.stats()
    # the bounded window reports 2 samples; the lifetime counters 5 —
    # the window-only-truth bug this satellite fixes
    assert st["latency_ms"]["count"] == 2
    assert st["requests_total"] == 5
    assert st["queries_total"] == 15


def test_queue_coalescing_preserves_per_request_trace_ids(placed):
    from knn_tpu.serving.engine import ServingEngine
    from knn_tpu.serving.queue import QueryQueue

    prog, rng = placed
    eng = ServingEngine(prog, buckets=(8, 16, 32))
    eng.warmup()
    reqs = [rng.standard_normal((s, 16)).astype(np.float32)
            for s in (2, 3, 4, 5)]
    with QueryQueue(eng, max_wait_ms=200.0) as qq:
        futs = [qq.submit(r) for r in reqs]
        ref = [eng.submit(r).result() for r in reqs]  # direct ground truth
        got = [f.result(timeout=60) for f in futs]
        st = qq.stats()
    # coalesced: fewer engine dispatches than requests, results intact
    assert st["dispatches"] < st["requests"] == len(reqs)
    for (gd, gi), (rd, ri) in zip(got, ref):
        np.testing.assert_array_equal(gi, ri)
        np.testing.assert_array_equal(gd, rd)

    evts = obs.get_event_log().recent()
    waits = [e for e in evts if e.get("span") == "serving.queue_wait"]
    done = [e for e in evts if e.get("span") == "serving.queued_request"]
    # one trace id per REQUEST, unique, consistent across its spans —
    # even though the requests rode one coalesced engine dispatch
    wait_ids = [e["trace_id"] for e in waits]
    assert len(wait_ids) == len(reqs) and len(set(wait_ids)) == len(reqs)
    assert sorted(e["trace_id"] for e in done) == sorted(wait_ids)
    disp = [e for e in evts if e.get("name") == "queue.dispatch"]
    members = [tid for e in disp for tid in e["member_trace_ids"]]
    assert sorted(members) == sorted(wait_ids)
    # the batch-level engine trace id is linked from every member join
    batch_ids = {e["batch_trace_id"] for e in disp}
    assert {e["batch_trace_id"] for e in done} <= batch_ids
    # queue lifetime counters in the registry match ground truth
    assert obs.counter(mn.QUEUE_REQUESTS).get() == len(reqs)
    assert obs.counter(mn.QUEUE_COALESCED_ROWS).get() == 14.0
    # depth gauges drained back to zero
    assert obs.gauge(mn.QUEUE_DEPTH_REQUESTS).get() == 0.0
    assert obs.gauge(mn.QUEUE_DEPTH_ROWS).get() == 0.0


# --- certified search ground truth --------------------------------------
def test_certified_counters_match_stats(placed):
    prog, rng = placed
    q = rng.standard_normal((12, 16)).astype(np.float32)
    _, _, stats = prog.search_certified(q, selector="approx", margin=8)
    assert obs.counter(
        mn.CERTIFIED_QUERIES, selector="approx").get() == 12.0
    assert obs.counter(
        mn.CERTIFIED_FALLBACKS, selector="approx").get() == float(
            stats["fallback_queries"])
    assert obs.counter(
        mn.CERTIFIED_GENUINE_MISSES, selector="approx").get() == float(
            stats.get("fallback_genuine_misses", 0))


def test_int8_quant_bound_distribution_recorded(rng):
    from knn_tpu.parallel import ShardedKNN, make_mesh
    from knn_tpu.ops.quantize import score_error_bound

    db = rng.integers(0, 256, size=(900, 16), dtype=np.uint8)
    q = rng.integers(0, 256, size=(7, 16)).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(2, 4), k=4)
    prog.search_certified(
        q, selector="pallas", margin=8, tile_n=256, precision="int8")
    s = obs.snapshot()[mn.CERTIFIED_QUANT_BOUND]["series"][0]["value"]
    assert s["count"] == q.shape[0]
    pl8 = prog._int8_cache
    eps = score_error_bound(q, pl8["stats"], offset=pl8["offset"])
    assert s["max"] == pytest.approx(float(np.max(eps)))
    assert s["min"] == pytest.approx(float(np.min(eps)))


def test_results_bitwise_identical_obs_on_vs_off(placed, tmp_path,
                                                 monkeypatch):
    prog, rng = placed
    q = rng.standard_normal((8, 16)).astype(np.float32)
    d_on, i_on, _ = prog.search_certified(q, selector="approx", margin=8)
    obs.reset(enabled=False)
    d_off, i_off, _ = prog.search_certified(q, selector="approx", margin=8)
    # instrumentation never touches numerics: disabled vs enabled output
    # is bitwise identical
    np.testing.assert_array_equal(i_on, i_off)
    np.testing.assert_array_equal(d_on, d_off)
    # ...and no tail-forensics work happens either: exemplars are the
    # shared no-op, reconstruction has nothing to read, and the flight
    # recorder stays disarmed even with a destination configured
    from knn_tpu.obs import blackbox, waterfall

    h = obs.histogram(mn.SERVING_REQUEST_LATENCY, op="search")
    h.observe(1.0, exemplar="feed000000000001")
    assert h.exemplars() == []
    assert waterfall.slowest_table() == []
    monkeypatch.setenv(blackbox.DIR_ENV, str(tmp_path / "pm"))
    assert not blackbox.enabled()
    assert blackbox.on_breach("serving_availability", {}) is None
    assert not (tmp_path / "pm").exists()


# --- tuning counters -----------------------------------------------------
def test_tuning_counters_mirrored_to_registry(tmp_path, monkeypatch):
    from knn_tpu import tuning

    monkeypatch.setenv("KNN_TPU_TUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    before = obs.counter(mn.TUNING_RESOLVES).get()
    miss_before = obs.counter(mn.TUNING_CACHE_MISSES).get()
    tuning.resolve(1000, 16, 5)
    assert obs.counter(mn.TUNING_RESOLVES).get() == before + 1
    assert obs.counter(mn.TUNING_CACHE_MISSES).get() == miss_before + 1


# --- compile hook --------------------------------------------------------
def test_jax_compile_events_counted():
    if not obs.install_compile_hook():
        pytest.skip("jax.monitoring listener API unavailable")
    import jax
    import jax.numpy as jnp

    # a shape this process has never compiled: forces a fresh compile
    x = jnp.arange(677.0)
    jax.jit(lambda v: v * 3.0 + 1.0)(x).block_until_ready()
    snap = obs.snapshot()
    assert mn.JAX_COMPILES in snap
    assert sum(s["value"] for s in snap[mn.JAX_COMPILES]["series"]) >= 1
    secs = sum(s["value"]
               for s in snap[mn.JAX_COMPILE_SECONDS]["series"])
    assert secs > 0


# --- JSONL sink rotation -------------------------------------------------
def test_jsonl_sink_rotates_preserving_valid_jsonl(tmp_path):
    """A long-running process's event log is size-capped: when the cap
    is crossed the file rotates to <path>.1 via atomic rename, on a
    LINE boundary — both sides of the cut must parse as valid JSONL and
    jointly hold every emitted event."""
    path = tmp_path / "events.jsonl"
    # each event line is ~70 bytes; a 1 KiB cap forces several cuts
    obs.reset_event_log(str(path), max_bytes=1024)
    n = 200
    for i in range(n):
        obs.emit_event("queue.dispatch", rows=i, pad="x" * 16)
    assert path.exists() and (tmp_path / "events.jsonl.1").exists()
    live = [json.loads(ln) for ln in path.read_text().splitlines()]
    rotated = [json.loads(ln) for ln in
               (tmp_path / "events.jsonl.1").read_text().splitlines()]
    # both generations are whole JSON lines, under the cap, and the
    # newest events are in the live file in order
    assert all(e["name"] == "queue.dispatch" for e in live + rotated)
    assert path.stat().st_size <= 1024
    assert (tmp_path / "events.jsonl.1").stat().st_size <= 1024
    assert [e["rows"] for e in rotated + live] == list(
        range(n - len(rotated) - len(live), n))
    # the in-memory ring still holds everything regardless of rotation
    assert len(obs.get_event_log().recent()) == n


def test_jsonl_rotation_keeps_exactly_two_generations(tmp_path):
    path = tmp_path / "e.jsonl"
    obs.reset_event_log(str(path), max_bytes=256)
    for i in range(300):
        obs.emit_event("queue.dispatch", rows=i)
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["e.jsonl", "e.jsonl.1"]  # older generations replaced


# --- metrics server under concurrent load --------------------------------
def test_http_server_concurrent_load_never_tears(placed):
    """Hammer /metrics, /metrics.json, /healthz, /statusz from several
    threads while the registry mutates underneath: every response must
    parse (text exposition / JSON), and no torn snapshot may surface —
    the server's view is always a consistent point-in-time read."""
    import urllib.error

    from knn_tpu.serving.engine import ServingEngine

    prog, rng = placed
    eng = ServingEngine(prog, buckets=(8,))
    eng.warmup()
    server = obs.start_metrics_server(0)
    errors = []
    stop = threading.Event()
    try:
        port = server.server_address[1]

        def mutate():
            i = 0
            while not stop.is_set():
                obs.counter(mn.QUEUE_REQUESTS).inc()
                obs.histogram(mn.QUEUE_WAIT).observe(i * 1e-4)
                obs.gauge(mn.QUEUE_DEPTH_ROWS).set(i % 7)
                i += 1

        def fetch(path, check):
            try:
                for _ in range(25):
                    try:
                        body = urllib.request.urlopen(
                            f"http://127.0.0.1:{port}{path}",
                            timeout=10).read().decode()
                    except urllib.error.HTTPError as e:
                        body = e.read().decode()  # /healthz 503 is fine
                    check(body)
            except Exception as e:  # noqa: BLE001 — the assertion surface
                errors.append((path, repr(e)))

        def check_prom(body):
            assert "# TYPE knn_tpu_queue_requests_total counter" in body
            for ln in body.splitlines():
                assert ln.startswith("#") or " " in ln

        def check_json(body):
            json.loads(body)

        mut = threading.Thread(target=mutate, daemon=True)
        mut.start()
        ts = []
        for _ in range(2):
            for path, check in (("/metrics", check_prom),
                                ("/metrics.json", check_json),
                                ("/healthz", check_json),
                                ("/statusz", check_json)):
                ts.append(threading.Thread(target=fetch,
                                           args=(path, check)))
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        stop.set()
        mut.join(10)
        assert not errors, errors
    finally:
        stop.set()
        server.shutdown()


# --- the lint gate -------------------------------------------------------
def test_lint_metric_names_green():
    r = subprocess.run(
        [sys.executable, f"{REPO}/scripts/lint_metric_names.py"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
