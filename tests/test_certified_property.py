"""Property-based exactness: for ANY database/query/k/margin drawn by
hypothesis, every certified selector must reproduce the float64 oracle's
lexicographic top-k bit-for-bit.  This is the suite's randomized sweep of
the shapes the hand-written fixtures don't enumerate — tie pileups,
degenerate margins, k=1, n barely above k, non-multiple-of-bin sizes.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings, strategies as st  # noqa: E402

import oracles  # noqa: E402 — tests/oracles.py: THE oracle semantics

from knn_tpu.parallel import ShardedKNN, make_mesh  # noqa: E402


def _oracle(db, queries, k):
    # tests/oracles.py is THE oracle-semantics home; topk_lowindex
    # already returns the (values, indices) pair
    return oracles.topk_lowindex(oracles.sq_l2(queries, db), k)


@settings(max_examples=12, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(
    seed=st.integers(0, 2**31 - 1),
    n=st.integers(8, 700),
    dim=st.integers(2, 24),
    k=st.integers(1, 12),
    margin=st.integers(0, 24),
    dup_frac=st.floats(0.0, 0.4),
    selector=st.sampled_from(["exact", "approx"]),
)
def test_counted_certified_matches_oracle(seed, n, dim, k, margin, dup_frac,
                                          selector):
    rng = np.random.default_rng(seed)
    k = min(k, n)
    db = rng.normal(size=(n, dim)).astype(np.float32) * 10
    n_dup = int(n * dup_frac)
    if n_dup:
        # duplicate rows force exact ties -> the lexicographic tie-break
        # and the strict-count certificate must both hold
        db[rng.choice(n, n_dup, replace=False)] = db[
            rng.choice(n, n_dup, replace=True)]
    queries = rng.normal(size=(7, dim)).astype(np.float32) * 10
    ref_d, ref_i = _oracle(db, queries, k)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=k)
    d, i, stats = prog.search_certified(queries, selector=selector,
                                        margin=margin)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9, atol=1e-9)
    assert stats["certified"] + stats["fallback_queries"] == 7


@settings(max_examples=8, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(
    seed=st.integers(0, 2**31 - 1),
    n_tiles=st.integers(2, 5),
    extra=st.integers(0, 127),
    dim=st.integers(2, 16),
    k=st.integers(1, 9),
    final_select=st.sampled_from(["exact", "approx"]),
    binning=st.sampled_from(["grouped", "lane"]),
    grid_order=st.sampled_from(["query_major", "db_major"]),
)
def test_pallas_certified_matches_oracle_property(seed, n_tiles, extra, dim,
                                                  k, final_select, binning,
                                                  grid_order):
    rng = np.random.default_rng(seed)
    n = n_tiles * 128 + extra
    db = rng.normal(size=(n, dim)).astype(np.float32) * 10
    db[n // 2: n // 2 + 10] = db[:10]  # cross-bin exact ties
    queries = rng.normal(size=(5, dim)).astype(np.float32) * 10
    ref_d, ref_i = _oracle(db, queries, k)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=k)
    d, i, stats = prog.search_certified(
        queries, selector="pallas", margin=8, tile_n=256,
        final_select=final_select, binning=binning, grid_order=grid_order,
    )
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)
