"""Checkpoint/resume streaming tests: interruption loses at most one batch,
resume completes without recomputing finished work, wrong-run checkpoints
are rejected."""

import numpy as np
import pytest

from knn_tpu.ops.topk import knn_search
from knn_tpu.parallel import make_mesh
from knn_tpu.streaming import (
    StreamingCertifiedSearch,
    StreamingSearch,
    _fingerprint,
    streaming_certified_knn,
    streaming_knn,
)

import jax.numpy as jnp


@pytest.fixture
def data(rng):
    db = rng.normal(size=(300, 12)).astype(np.float32)
    queries = rng.normal(size=(70, 12)).astype(np.float32)
    return db, queries


def _ref(db, queries, k):
    d, i = knn_search(jnp.asarray(queries), jnp.asarray(db), k)
    return np.asarray(d), np.asarray(i)


def test_streaming_matches_direct(tmp_path, data):
    db, queries = data
    d, i = streaming_knn(
        db, queries, 5, str(tmp_path / "ckpt"), mesh=make_mesh(4, 2), batch_size=16
    )
    ref_d, ref_i = _ref(db, queries, 5)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-5)


def test_streaming_resumes_after_interruption(tmp_path, data):
    db, queries = data
    ckpt = str(tmp_path / "ckpt")
    calls = []

    def flaky(chunk):
        calls.append(1)
        if len(calls) == 3:
            raise KeyboardInterrupt  # simulated preemption, not retried
        return _ref(db, chunk, 5)

    stream = StreamingSearch(flaky, 5, ckpt, batch_size=16, max_retries=0)
    with pytest.raises(KeyboardInterrupt):
        stream.run(queries)
    st = stream.state(queries.shape[0])
    assert len(st.done) == 2 and not st.complete  # two batches survived

    # resume with a healthy fn: only the remaining 3 of 5 batches run
    calls2 = []

    def healthy(chunk):
        calls2.append(1)
        return _ref(db, chunk, 5)

    stream2 = StreamingSearch(healthy, 5, ckpt, batch_size=16)
    d, i = stream2.run(queries)
    assert len(calls2) == 3
    ref_d, ref_i = _ref(db, queries, 5)
    np.testing.assert_array_equal(i, ref_i)


def test_streaming_retries_transient_failures(tmp_path, data):
    db, queries = data
    fails = {"left": 2}

    def transient(chunk):
        if fails["left"]:
            fails["left"] -= 1
            # transient vocabulary: keeps the full retry window even
            # when attempts fail identically (sharded._classify_failure)
            raise RuntimeError("UNAVAILABLE: simulated device loss")
        return _ref(db, chunk, 4)

    stream = StreamingSearch(transient, 4, str(tmp_path / "c"), batch_size=70, max_retries=2)
    d, i = stream.run(queries)
    ref_d, ref_i = _ref(db, queries, 4)
    np.testing.assert_array_equal(i, ref_i)


def test_streaming_exhausted_retries_raise(tmp_path, data):
    db, queries = data

    def always_fails(chunk):
        raise RuntimeError("dead device")

    stream = StreamingSearch(always_fails, 4, str(tmp_path / "c"), batch_size=70, max_retries=1)
    with pytest.raises(RuntimeError, match="after 2 attempts"):
        stream.run(queries)


def test_streaming_rejects_wrong_run(tmp_path, data):
    db, queries = data
    ckpt = str(tmp_path / "ckpt")
    streaming_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1), batch_size=16)
    with pytest.raises(ValueError, match="different run"):
        streaming_knn(db, queries, 7, ckpt, mesh=make_mesh(8, 1), batch_size=16)
    other_db = db + 1.0
    with pytest.raises(ValueError, match="different run"):
        streaming_knn(other_db, queries, 5, ckpt, mesh=make_mesh(8, 1), batch_size=16)


def test_streaming_rejects_different_queries_or_metric(tmp_path, data):
    # same shapes, different content/config: must NOT silently reuse batches
    db, queries = data
    ckpt = str(tmp_path / "ckpt")
    streaming_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1), batch_size=16)
    other_queries = queries + 0.5
    with pytest.raises(ValueError, match="different run"):
        streaming_knn(db, other_queries, 5, ckpt, mesh=make_mesh(8, 1), batch_size=16)
    with pytest.raises(ValueError, match="different run"):
        streaming_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1), batch_size=16,
                      metric="cosine")


def test_streaming_incomplete_assemble_raises(tmp_path, data):
    db, queries = data
    stream = StreamingSearch(lambda c: _ref(db, c, 3), 3, str(tmp_path / "c"), batch_size=16)
    with pytest.raises(RuntimeError, match="incomplete"):
        stream.assemble(queries.shape[0])


def test_certified_streaming_matches_direct(tmp_path, data):
    # the certified path through the checkpoint stream must equal a
    # direct one-shot search_certified call — distances, indices, AND
    # summed outcome stats
    from knn_tpu.parallel.sharded import ShardedKNN

    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5)
    ref_d, ref_i, ref_stats = prog.search_certified(
        queries, selector="pallas", margin=8)

    d, i, stats = streaming_certified_knn(
        db, queries, 5, str(tmp_path / "ckpt"), mesh=make_mesh(4, 2),
        segment_size=16, selector="pallas", margin=8)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)  # bitwise: same fp path
    assert stats["certified"] + stats["fallback_queries"] == queries.shape[0]


def test_certified_streaming_resumes_bitwise_identical(tmp_path, data):
    # VERDICT r4 item 3 done-bar: kill a certified stream mid-run,
    # resume, and the assembled output is BITWISE identical to an
    # uninterrupted run — including the persisted per-segment stats
    from knn_tpu.parallel.sharded import ShardedKNN

    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5)

    def certified(chunk):
        return prog.search_certified(chunk, selector="pallas", margin=8)

    # uninterrupted control run
    ctl = StreamingCertifiedSearch(
        certified, 5, str(tmp_path / "ctl"), batch_size=16,
        db_fingerprint=_fingerprint(db))
    cd, ci, cstats = ctl.run(queries)

    # interrupted run: die on segment 3 of 5
    calls = []

    def dying(chunk):
        calls.append(1)
        if len(calls) == 3:
            raise KeyboardInterrupt  # simulated preemption, not retried
        return certified(chunk)

    ckpt = str(tmp_path / "ckpt")
    stream = StreamingCertifiedSearch(
        dying, 5, ckpt, batch_size=16, db_fingerprint=_fingerprint(db),
        max_retries=0)
    with pytest.raises(KeyboardInterrupt):
        stream.run(queries)
    st = stream.state(queries.shape[0])
    assert len(st.done) == 2 and not st.complete

    # resume: only the remaining 3 of 5 segments run
    resumed = []

    def healthy(chunk):
        resumed.append(1)
        return certified(chunk)

    stream2 = StreamingCertifiedSearch(
        healthy, 5, ckpt, batch_size=16, db_fingerprint=_fingerprint(db))
    d, i, stats = stream2.run(queries)
    assert len(resumed) == 3
    np.testing.assert_array_equal(i, ci)
    np.testing.assert_array_equal(d, cd)
    assert stats == cstats


def test_certified_streaming_labels_only_and_stats_persist(tmp_path, data):
    # return_distances=False flows through: d is None, indices exact,
    # stats still persisted per segment and summed on assembly
    from knn_tpu.parallel.sharded import ShardedKNN

    db, queries = data
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5)
    _, ref_i, _ = prog.search_certified(queries, selector="pallas", margin=8)
    d, i, stats = streaming_certified_knn(
        db, queries, 5, str(tmp_path / "c"), mesh=make_mesh(4, 2),
        segment_size=32, selector="pallas", margin=8,
        return_distances=False)
    assert d is None
    np.testing.assert_array_equal(i, ref_i)
    assert "fallback_queries" in stats and "certified" in stats


def test_certified_streaming_rejects_different_knobs(tmp_path, data):
    # finished segments computed under different certified knobs are a
    # DIFFERENT run — the manifest must refuse, never silently reuse
    db, queries = data
    ckpt = str(tmp_path / "ckpt")
    streaming_certified_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1),
                            segment_size=32, selector="pallas", margin=8)
    with pytest.raises(ValueError, match="different run"):
        streaming_certified_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1),
                                segment_size=32, selector="exact", margin=8)
    with pytest.raises(ValueError, match="different run"):
        streaming_certified_knn(db, queries, 5, ckpt, mesh=make_mesh(8, 1),
                                segment_size=32, selector="pallas", margin=12)


def test_fingerprint_sensitivity(data):
    db, _ = data
    assert _fingerprint(db) != _fingerprint(db + 1e-3)
    assert _fingerprint(db) == _fingerprint(db.copy())
