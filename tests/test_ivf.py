"""IVF tier (knn_tpu.ivf): probe-pruned streaming that stays exact.

The pinned contracts, in ISSUE order: deterministic seeded k-means;
clustered data at nprobe < ncentroids streams <= 1/4 of the brute-force
db bytes (priced with the roofline operand byte model) while every
final answer stays bitwise-equal to exact brute force; the certificate
DETECTS forced probe misses and the exact fallback repairs them;
nprobe = ncentroids reproduces the non-IVF exact anchor bitwise across
selectors, precisions, and kernels; the PR-13 mutation oracle extends
to IVF across interleavings and re-cluster compactions; the live
mixed-traffic harness crosses >= 2 background swaps with flat admitted
p99; the ivf artifact block validates; MODEL_VERSION 5 prices probed
bytes and the cli threads --nprobe/--ncentroids."""

import threading
import time

import numpy as np
import pytest

from knn_tpu import loadgen, obs
from knn_tpu.index.artifact import MutationBudgetError
from knn_tpu.ivf import IVFIndex, SELECTORS, train_kmeans
from knn_tpu.ivf.artifact import IVF_VERSION, validate_ivf_block
from knn_tpu.ops.refine import refine_shared_exact
from knn_tpu.parallel.mesh import make_mesh

DIM = 16
K = 5
NCLUSTERS = 8


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset(enabled=True)
    yield
    obs.reset()


def _clustered(rng, per=40, spread=0.05, sep=20.0):
    """Well-separated gaussian blobs: the workload IVF exists for."""
    cents = (rng.normal(size=(NCLUSTERS, DIM)) * sep).astype(np.float32)
    rows = np.concatenate([
        cents[i] + rng.normal(size=(per, DIM)).astype(np.float32) * spread
        for i in range(NCLUSTERS)])
    qs = (cents[rng.integers(0, NCLUSTERS, 24)]
          + rng.normal(size=(24, DIM)).astype(np.float32) * spread)
    return rows, qs


def _exact(db, q, k=K):
    """The brute-force oracle: the SAME f64 refine anchor every
    non-IVF certified final answer resolves through."""
    return refine_shared_exact(
        db, q, np.arange(db.shape[0], dtype=np.int64), k)


@pytest.fixture(scope="module")
def clustered():
    rng = np.random.default_rng(7)
    rows, qs = _clustered(rng)
    return rows, qs


# -- k-means ----------------------------------------------------------------
def test_kmeans_seeded_deterministic(clustered):
    rows, _ = clustered
    mesh = make_mesh()
    a = train_kmeans(rows, NCLUSTERS, mesh=mesh, iters=4, seed=3)
    b = train_kmeans(rows, NCLUSTERS, mesh=mesh, iters=4, seed=3)
    assert np.array_equal(a.centroids, b.centroids)
    assert np.array_equal(a.assign, b.assign)
    assert a.counts.sum() == rows.shape[0]
    assert (a.residuals >= 0).all()
    # the residual really bounds every member's distance to its centroid
    d = np.linalg.norm(rows.astype(np.float64)
                       - a.centroids.astype(np.float64)[a.assign], axis=1)
    assert (d <= a.residuals[a.assign] + 1e-12).all()


# -- the pruning pin --------------------------------------------------------
def test_clustered_probe_streams_quarter_of_brute_force(clustered):
    """The acceptance bar: nprobe < ncentroids on clusterable data
    streams <= 1/4 the db bytes of brute force (operand byte model),
    fully certified, and the final (d, i) are bitwise brute force."""
    rows, qs = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=1, train_iters=4, seed=0)
    d_i, i_i, st = idx.search_certified(qs)
    d_ref, i_ref = _exact(rows, qs)
    assert np.array_equal(i_i, i_ref)
    assert np.array_equal(d_i, d_ref)
    assert st["fallback_rate"] == 0.0
    assert st["certified_queries"] == qs.shape[0]
    assert st["recall_at_k"] == 1.0
    assert st["bytes_streamed_ratio"] <= 0.25, st
    assert st["probe_fraction"] <= 0.25, st


@pytest.mark.parametrize("selector", SELECTORS)
def test_nprobe_all_reproduces_exact_bitwise(clustered, selector):
    rows, qs = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=NCLUSTERS, train_iters=2, seed=0)
    d_i, i_i, st = idx.search_certified(qs, selector=selector,
                                        margin=8, tile_n=256)
    d_ref, i_ref = _exact(rows, qs)
    assert np.array_equal(i_i, i_ref)
    assert np.array_equal(d_i, d_ref)
    assert st["probe_fraction"] == 1.0


@pytest.mark.parametrize("precision,kernel", [
    ("highest", "tiled"), ("bf16x3", "streaming"), ("int8", "streaming"),
    ("bf16x3", "fused"),
])
def test_bitwise_across_pallas_precisions_and_kernels(
        clustered, precision, kernel):
    """End results are selector/precision/kernel-independent: every
    coarse pass only proposes candidates; the f64 refine anchor (and
    the certified fallback) decides."""
    rows, qs = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=2, train_iters=2, seed=0)
    d_i, i_i, st = idx.search_certified(
        qs, selector="pallas", precision=precision, kernel=kernel,
        margin=8, tile_n=256)
    d_ref, i_ref = _exact(rows, qs)
    assert np.array_equal(i_i, i_ref)
    assert np.array_equal(d_i, d_ref)


def test_forced_miss_is_detected_and_repaired(clustered):
    """Adversarial queries BETWEEN clusters at nprobe=1: the residual
    certificate must flag them (detected, never silent), the fallback
    must repair them to bitwise brute force, and the stats must say
    what happened."""
    rows, _ = clustered
    rng = np.random.default_rng(11)
    # midpoints of random cluster pairs: nearest neighbors straddle
    # two lists, so probing one cannot be certified
    cents = train_kmeans(rows, NCLUSTERS, mesh=make_mesh(), iters=4,
                         seed=0).centroids
    pairs = rng.choice(NCLUSTERS, size=(12, 2), replace=True)
    qs = ((cents[pairs[:, 0]] + cents[pairs[:, 1]]) / 2).astype(np.float32)
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=1, train_iters=4, seed=0)
    d_i, i_i, st = idx.search_certified(qs)
    d_ref, i_ref = _exact(rows, qs)
    assert np.array_equal(i_i, i_ref)
    assert np.array_equal(d_i, d_ref)
    assert st["fallback_queries"] > 0, st
    assert st["fallback_rate"] == st["fallback_queries"] / qs.shape[0]
    assert 0.0 <= st["recall_at_k"] <= 1.0


def test_env_switches_consumed(clustered, monkeypatch):
    rows, _ = clustered
    monkeypatch.setenv("KNN_TPU_IVF_NCENTROIDS", "4")
    monkeypatch.setenv("KNN_TPU_IVF_NPROBE", "3")
    monkeypatch.setenv("KNN_TPU_IVF_TRAIN_ITERS", "2")
    monkeypatch.setenv("KNN_TPU_IVF_SEED", "9")
    idx = IVFIndex(rows, mesh=make_mesh(), k=K)
    st = idx.stats()
    assert (st["ncentroids"], st["nprobe"]) == (4, 3)
    assert (st["train_iters"], st["seed"]) == (2, 9)


# -- mutability -------------------------------------------------------------
def test_write_contract_refusals(clustered):
    rows, _ = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   train_iters=2, seed=0)
    extra = rows[:2] + 1.0
    idx.insert(extra, [9000, 9001])
    with pytest.raises(ValueError, match="already live"):
        idx.insert(extra[:1], [9000])
    idx.delete([9000])
    with pytest.raises(ValueError, match="compact"):
        idx.insert(extra[:1], [9000])  # tombstoned id needs compact()
    with pytest.raises(KeyError):
        idx.delete([424242])
    with pytest.raises(MutationBudgetError):
        small = IVFIndex(rows[:8], mesh=make_mesh(), k=K, ncentroids=2,
                         train_iters=1, seed=0)
        small.delete(list(range(4)))  # would leave live < k


def test_mutation_oracle_across_compactions(clustered):
    """The PR-13 oracle, extended: after ANY interleaving of inserts,
    deletes, and re-cluster compactions, certified IVF search is
    bitwise-identical to a fresh exact index of the surviving rows —
    for the counted selector AND the pallas coarse path."""
    rows, qs = clustered
    rng = np.random.default_rng(3)
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=2, train_iters=2, seed=0)
    n0 = rows.shape[0]
    ins1 = rows[:30] + rng.normal(size=(30, DIM)).astype(np.float32)
    idx.insert(ins1, np.arange(n0, n0 + 30))
    idx.delete(np.arange(0, 20))
    rep1 = idx.compact()
    assert rep1["epoch"] == 1
    ins2 = rows[40:55] + rng.normal(size=(15, DIM)).astype(np.float32)
    idx.insert(ins2, np.arange(n0 + 30, n0 + 45))
    idx.delete(np.arange(25, 35))
    rep2 = idx.compact()
    assert rep2["epoch"] == 2
    assert idx.stats()["compactions"] == 2

    # survivors in canonical order: base insertion order then tails
    surv_rows = np.concatenate([rows[20:25], rows[35:], ins1, ins2])
    surv_ids = np.concatenate([
        np.arange(20, 25), np.arange(35, n0), np.arange(n0, n0 + 45)])
    d_ref, p_ref = refine_shared_exact(
        surv_rows, qs, np.arange(surv_rows.shape[0], dtype=np.int64), K)
    i_ref = surv_ids[p_ref]
    for sel in SELECTORS:
        d_i, i_i, _ = idx.search_certified(qs, selector=sel, margin=8,
                                           tile_n=256)
        assert np.array_equal(i_i, i_ref), sel
        assert np.array_equal(d_i, d_ref), sel
    # and a fresh IVF index over the survivors agrees with itself
    fresh = IVFIndex(surv_rows, surv_ids, mesh=make_mesh(), k=K,
                     ncentroids=NCLUSTERS, nprobe=2, train_iters=2,
                     seed=0)
    d_f, i_f, _ = fresh.search_certified(qs)
    assert np.array_equal(i_f, i_ref)
    assert np.array_equal(d_f, d_ref)


def test_concurrent_reads_during_writes(clustered):
    """Snapshot isolation: readers racing writes + a compaction always
    see a consistent corpus (every returned id was live in SOME epoch;
    results equal the oracle of the snapshot they read)."""
    rows, qs = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=2, train_iters=2, seed=0)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            try:
                d_i, i_i, _ = idx.search_certified(qs[:4])
                assert d_i.shape == (4, K) and (i_i >= 0).all()
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                return

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    n0 = rows.shape[0]
    for b in range(4):
        idx.insert(rows[:5] + np.float32(b + 1),
                   np.arange(n0 + 5 * b, n0 + 5 * (b + 1)))
    idx.compact()
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors


def test_live_mixed_traffic_across_swaps(clustered):
    """The serving bar: loadgen read+write mix on the IVF engine stays
    error-free with flat admitted p99 across >= 2 background
    re-cluster swaps."""
    from knn_tpu.serving.queue import QueryQueue

    rows, _ = clustered
    rng = np.random.default_rng(13)
    pool = rng.normal(size=(64, DIM)).astype(np.float32)
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=2, train_iters=1, seed=0,
                   compact_tail_rows=6)
    eng = idx.serving_engine(buckets=(8, 16))
    eng.warmup()
    idx.start_compactor()
    spec = loadgen.WorkloadSpec(
        rate_qps=150, duration_s=1.2, seed=13,
        tenants=(
            loadgen.TenantSpec("readers", weight=0.8,
                               batch_sizes=(1, 2, 4)),
            loadgen.TenantSpec("writers", weight=0.2, batch_sizes=(1,),
                               insert_fraction=0.6,
                               delete_fraction=0.3),
        ))
    reqs = loadgen.generate(spec)
    assert any(r.kind == "insert" for r in reqs)
    try:
        with QueryQueue(eng, max_wait_ms=2.0) as qq:
            rep = loadgen.run_workload(qq, reqs, queries=pool)
    finally:
        idx.close()
    swaps = idx.stats()["compactions"]
    assert swaps >= 2, f"only {swaps} compaction swap(s) happened"
    assert rep["writes"]["insert"].get("ok", 0) >= 6
    assert rep["errors"] == 0, rep["outcomes"]
    lat = rep["latency_ms"]
    assert lat and lat["p99"] < 500.0, lat


# -- the ivf artifact block -------------------------------------------------
def _good_block():
    return {
        "ivf_version": IVF_VERSION,
        "ncentroids": 32, "nprobe": 8, "queries": 128, "k": 10,
        "probe_fraction": 0.25, "recall_at_k": 1.0,
        "fallback_rate": 0.0, "bytes_streamed_ratio": 0.25,
        "qps": 1234.5, "selector": "exact",
        "fallback_queries": 0, "certified_queries": 128,
        "genuine_misses": 0, "epoch": 0, "compactions": 0,
    }


def test_ivf_block_validator():
    assert validate_ivf_block(_good_block()) == []
    bad = _good_block()
    del bad["probe_fraction"]
    assert any("probe_fraction" in e for e in validate_ivf_block(bad))
    bad = _good_block()
    bad["ivf_version"] = IVF_VERSION + 1
    assert validate_ivf_block(bad)
    bad = _good_block()
    bad["recall_at_k"] = 1.5
    assert validate_ivf_block(bad)


def test_search_stats_validate_as_block(clustered):
    """The bench emitter builds its block from these stats: the
    live-measured fields must satisfy the cataloged schema ranges."""
    rows, qs = clustered
    idx = IVFIndex(rows, mesh=make_mesh(), k=K, ncentroids=NCLUSTERS,
                   nprobe=2, train_iters=2, seed=0)
    _, _, st = idx.search_certified(qs)
    ist = idx.stats()
    block = {
        "ivf_version": IVF_VERSION,
        "ncentroids": st["ncentroids"], "nprobe": st["nprobe"],
        "queries": st["queries"], "k": st["k"],
        "probe_fraction": st["probe_fraction"],
        "recall_at_k": st["recall_at_k"],
        "fallback_rate": st["fallback_rate"],
        "bytes_streamed_ratio": st["bytes_streamed_ratio"],
        "qps": 100.0, "selector": st["selector"],
        "fallback_queries": st["fallback_queries"],
        "certified_queries": st["certified_queries"],
        "genuine_misses": st["genuine_misses"],
        "epoch": ist["epoch"], "compactions": ist["compactions"],
    }
    assert validate_ivf_block(block) == []


# -- the autotuner gate -----------------------------------------------------
def test_autotune_ivf_bitwise_gate(clustered):
    from knn_tpu import tuning

    rows, qs = clustered
    grid = [{"ncentroids": NCLUSTERS, "nprobe": 1},
            {"ncentroids": NCLUSTERS, "nprobe": 2},
            {"ncentroids": NCLUSTERS, "nprobe": NCLUSTERS}]
    entry = tuning.autotune_ivf(rows, qs, K, mesh=make_mesh(), runs=1,
                                grid=grid, train_iters=2, seed=0)
    assert entry["gate"] == "bitwise-vs-reference"
    assert entry["winner"] in entry["timings_ms"]
    # every candidate passed the gate (the certified fallback makes
    # every sound placement bitwise-exact), so all were timed
    assert all(v is not None for v in entry["timings_ms"].values()), \
        entry["errors"]
    assert entry["stats_per_candidate"][
        f"c{NCLUSTERS}p{NCLUSTERS}"]["probe_fraction"] == 1.0


def test_ivf_grid_always_carries_the_exact_anchor():
    from knn_tpu import tuning

    for n in (100, 5000, 100000):
        grid = tuning.ivf_grid(n)
        ccs = {c["ncentroids"] for c in grid}
        for cc in ccs:
            assert {"ncentroids": cc, "nprobe": cc} in grid


# -- roofline v5 + cli ------------------------------------------------------
def test_roofline_v5_prices_probed_bytes():
    """The pinned planning claim: at the SIFT1M int8 x streaming
    shape, probing 1 of 8 lists cuts the db stream bytes by exactly
    the pruning factor and lifts the modeled ceiling by ~ that factor;
    un-probed blocks are numerically unchanged from v4 arithmetic."""
    from knn_tpu.obs import roofline

    assert roofline.MODEL_VERSION >= 5  # probe term landed in v5
    shape = dict(n=1_000_000, d=128, k=100, nq=4096, precision="int8",
                 kernel="streaming", device_kind="TPU v5e")
    base = roofline.pallas_cost_model(**shape)
    ivf = roofline.pallas_cost_model(**shape, nprobe=1, ncentroids=8)
    assert "probe" not in base["terms"]
    pr = ivf["terms"]["probe"]
    assert pr["probe_fraction"] == 0.125
    assert pr["rows_probed"] == 125_000
    # db stream bytes scale by EXACTLY the pruning factor
    assert (ivf["terms"]["hbm"]["bytes"]["db_stream"] * 8
            == base["terms"]["hbm"]["bytes"]["db_stream"])
    # ceiling exceeds the non-IVF ceiling by ~ the pruning factor
    ratio = ivf["ceiling_qps"] / base["ceiling_qps"]
    assert 6.0 <= ratio <= 8.1, ratio
    # config keeps the TOTAL corpus size; the probe knobs ride beside
    assert ivf["config"]["n"] == 1_000_000
    assert (ivf["config"]["nprobe"], ivf["config"]["ncentroids"]) == (1, 8)
    # probed blocks never claim a measured ceiling
    assert ivf["calibration"]["applied"] is False
    # the xla family prices the same substitution
    x = roofline.xla_cost_model(n=1_000_000, d=128, k=100, nq=4096,
                                device_kind="TPU v5e",
                                nprobe=1, ncentroids=8)
    assert x["terms"]["probe"]["rows_probed"] == 125_000
    with pytest.raises(ValueError, match="together"):
        roofline.pallas_cost_model(n=10, d=4, k=1, nq=1, nprobe=2)


def test_roofline_render_shows_probed_term():
    from knn_tpu.obs import roofline

    block = roofline.pallas_cost_model(
        n=100_000, d=32, k=10, nq=256, precision="int8",
        kernel="streaming", device_kind="TPU v5e",
        nprobe=2, ncentroids=16)
    text = roofline.render_text(block)
    assert "probed:" in text and "nprobe 2/16" in text


def test_cli_roofline_ivf_flags(capsys):
    from knn_tpu import cli

    args = cli.build_roofline_parser().parse_args(
        ["--n", "1000000", "--dim", "128", "--k", "100",
         "--precision", "int8", "--kernel", "streaming",
         "--device-kind", "TPU v5e", "--nprobe", "1",
         "--ncentroids", "8"])
    assert cli.run_roofline(args) == 0
    out = capsys.readouterr().out
    assert "probed:" in out and "roofline v7" in out
    # --best threads the knobs instead of silently ignoring them
    args = cli.build_roofline_parser().parse_args(
        ["--n", "1000000", "--dim", "128", "--k", "100",
         "--device-kind", "TPU v5e", "--nprobe", "1",
         "--ncentroids", "8", "--best", "2", "--json"])
    assert cli.run_roofline(args) == 0
    # one knob without the other refuses loudly
    args = cli.build_roofline_parser().parse_args(
        ["--n", "1000", "--dim", "8", "--nprobe", "2"])
    assert cli.run_roofline(args) == 2
