"""KNNClassifier estimator: the reference job as fit/predict, plus the
meshed (ShardedKNN-backed) and certified execution modes — all four
execution strategies must emit identical labels."""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu import KNNClassifier
from knn_tpu.data.datasets import make_blobs
from knn_tpu.parallel import make_mesh


@pytest.fixture
def data(rng):
    feats, labels = make_blobs(400, 8, 4, cluster_std=0.6, seed=2)
    return feats[:300], labels[:300], feats[300:], labels[300:]


def test_fit_predict_score(data):
    X, y, Q, yq = data
    clf = KNNClassifier(k=7, normalize=True, batch_size=32)
    acc = clf.fit(X, y).score(Q, yq)
    assert acc > 0.9
    d, i = clf.kneighbors(Q)
    assert d.shape == (100, 7) and i.shape == (100, 7)


def test_meshed_matches_single_device(data):
    X, y, Q, _ = data
    base = KNNClassifier(k=7, normalize=True).fit(X, y)
    ref = np.asarray(base.predict(Q))
    for mesh_shape, merge in (((4, 2), "allgather"), ((2, 4), "ring")):
        clf = KNNClassifier(
            k=7, normalize=True, mesh=make_mesh(*mesh_shape), merge=merge,
            batch_size=64,
        ).fit(X, y)
        np.testing.assert_array_equal(np.asarray(clf.predict(Q)), ref)
        d, i = clf.kneighbors(Q)
        db, ib = base.kneighbors(Q)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ib))


def test_certified_mode_matches_exact(data):
    X, y, Q, _ = data
    ref = np.asarray(KNNClassifier(k=7, normalize=True).fit(X, y).predict(Q))
    clf = KNNClassifier(
        k=7, normalize=True, mesh=make_mesh(4, 2), mode="certified",
        batch_size=33,
    ).fit(X, y)
    np.testing.assert_array_equal(np.asarray(clf.predict(Q)), ref)
    d, i = clf.kneighbors(Q)
    assert i.shape == (100, 7)


def test_certified_requires_mesh():
    with pytest.raises(ValueError, match="needs a mesh"):
        KNNClassifier(mode="certified")
    with pytest.raises(ValueError, match="unknown mode"):
        KNNClassifier(mode="fast")


def test_errors(data):
    X, y, Q, _ = data
    clf = KNNClassifier(k=5)
    with pytest.raises(RuntimeError, match="fit"):
        clf.predict(Q)
    with pytest.raises(ValueError, match="k="):
        KNNClassifier(k=10_000).fit(X, y)
    clf.fit(X, y)
    with pytest.raises(ValueError, match="queries"):
        clf.predict(Q[:, :3])


def test_tie_semantics_duplicate_rows(rng):
    # identical train rows with different labels: the vote must follow the
    # reference's first-to-reach-max rule via the lexicographic neighbor
    # order (lowest index first among equal distances)
    X = np.zeros((6, 4), np.float32)
    y = np.array([2, 1, 1, 0, 0, 0], np.int32)
    Q = np.zeros((1, 4), np.float32)
    # k=3: neighbors are rows 0,1,2 (indices tie-break) -> labels 2,1,1 -> 1
    pred = KNNClassifier(k=3).fit(X, y).predict(Q)
    assert int(pred[0]) == 1
    meshed = KNNClassifier(k=3, mesh=make_mesh(4, 2)).fit(X, y).predict(Q)
    assert int(meshed[0]) == 1


def test_refit_without_mesh_drops_old_program(data):
    X, y, Q, _ = data
    clf = KNNClassifier(k=5, mesh=make_mesh(4, 2)).fit(X, y)
    clf.mesh = None
    X2 = X + 100.0  # shifted database: predictions must come from X2
    clf.fit(X2, y)
    ref = np.asarray(KNNClassifier(k=5).fit(X2, y).predict(Q))
    np.testing.assert_array_equal(np.asarray(clf.predict(Q)), ref)


def test_certified_rejects_non_l2_at_construction():
    with pytest.raises(ValueError, match="l2 and cosine"):
        KNNClassifier(metric="l1", mode="certified", mesh=object())


def test_classifier_certified_cosine(rng):
    # cosine + certified now reaches the classifier surface (it routes
    # to ShardedKNN.search_certified's unit-vector l2 certificate)
    from knn_tpu.parallel.mesh import make_mesh

    import knn_tpu

    X = (rng.normal(size=(400, 10)) * np.linspace(
        0.5, 2, 400)[:, None]).astype(np.float32)
    y = (np.arange(400) % 3).astype(np.int32)
    Q = rng.normal(size=(11, 10)).astype(np.float32)
    cert = knn_tpu.KNNClassifier(k=5, metric="cosine", mode="certified",
                                 mesh=make_mesh(1, 1)).fit(X, y)
    plain = knn_tpu.KNNClassifier(k=5, metric="cosine").fit(X, y)
    np.testing.assert_array_equal(
        np.asarray(cert.predict(Q)), np.asarray(plain.predict(Q)))
