"""The perf-regression sentinel (knn_tpu.obs.sentinel +
scripts/perf_sentinel.py): on recorded bench-history fixtures a
synthetic 20% qps regression is flagged ``regress``, jitter within the
historical MAD stays ``ok``, and stale-marked lines never enter the
baseline — the acceptance surface of the sentinel ISSUE."""

import json
import subprocess
import sys

import pytest

from knn_tpu.obs import sentinel

REPO = __file__.rsplit("/tests/", 1)[0]

#: a tight recorded history: sift-shaped TPU lines across three rounds,
#: ~6000 q/s with ~±60 jitter (MAD 60 -> sigma ~89, sigma_rel ~1.5%)
HISTORY = [
    {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 6000.0,
     "device_phase_qps": 24000.0, "mfu": 0.03, "backend": "tpu",
     "measured_round": 1, "measured_at_commit": "aaa1111"},
    {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 6060.0,
     "device_phase_qps": 24100.0, "mfu": 0.031, "backend": "tpu",
     "measured_round": 2, "measured_at_commit": "bbb2222"},
    {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 5940.0,
     "device_phase_qps": 23900.0, "mfu": 0.029, "backend": "tpu",
     "measured_round": 3, "measured_at_commit": "ccc3333"},
    {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 6120.0,
     "device_phase_qps": 24150.0, "mfu": 0.031, "backend": "tpu",
     "measured_round": 4, "measured_at_commit": "ddd4444"},
]

#: a stale republication with an absurd value: must NEVER enter
STALE_LINE = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
              "value": 60000.0, "backend": "tpu", "stale": True,
              "measured_round": 1, "measured_at_commit": "aaa1111"}


def _baselines(extra=()):
    return sentinel.build_baselines(list(HISTORY) + list(extra))


def test_synthetic_20pct_regression_flagged_regress():
    base = _baselines()
    med = base["knn_qps_sift1m_n1000000_d128_k100|tpu|default"][
        "value"]["median"]
    line = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
            "backend": "tpu", "value": med * 0.8}
    v = sentinel.verdict_for_line(line, baselines=base)
    assert v["verdict"] == "regress"
    f = v["fields"]["value"]
    assert f["drop_rel"] == pytest.approx(0.2, abs=1e-6)
    assert f["effect_sigmas"] > 4


def test_jitter_within_historical_mad_stays_ok():
    base = _baselines()
    stats = base["knn_qps_sift1m_n1000000_d128_k100|tpu|default"]["value"]
    # one MAD below the median is, by construction, historical jitter
    line = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
            "backend": "tpu", "value": stats["median"] - stats["mad"]}
    v = sentinel.verdict_for_line(line, baselines=base)
    assert v["verdict"] == "ok"
    # and a faster-than-baseline run is trivially ok
    line["value"] = stats["median"] * 1.3
    assert sentinel.verdict_for_line(
        line, baselines=base)["verdict"] == "ok"


def test_between_the_bars_is_warn():
    base = _baselines()
    stats = base["knn_qps_sift1m_n1000000_d128_k100|tpu|default"]["value"]
    # ~6% below median: past max(2*sigma_rel~3%, 2%), short of the 10%
    # regression floor
    line = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
            "backend": "tpu", "value": stats["median"] * 0.94}
    v = sentinel.verdict_for_line(line, baselines=base)
    assert v["fields"]["value"]["verdict"] == "warn"


def test_stale_lines_never_enter_the_baseline():
    with_stale = _baselines(extra=[STALE_LINE])
    clean = _baselines()
    key = "knn_qps_sift1m_n1000000_d128_k100|tpu|default"
    assert with_stale[key]["value"] == clean[key]["value"]
    assert with_stale[key]["value"]["n"] == len(HISTORY)
    assert 60000.0 not in with_stale[key]["value"]["values"]


def test_same_commit_same_value_counts_once():
    dup = dict(HISTORY[0])  # same commit, same value: a republication
    base = _baselines(extra=[dup])
    key = "knn_qps_sift1m_n1000000_d128_k100|tpu|default"
    assert base[key]["value"]["n"] == len(HISTORY)
    # same commit, DIFFERENT value = a genuine re-measurement: counts
    remeasured = dict(HISTORY[0], value=6010.0)
    base = _baselines(extra=[remeasured])
    assert base[key]["value"]["n"] == len(HISTORY) + 1


def test_backend_and_precision_key_separately():
    cpu_line = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
                "value": 50.0, "backend": "cpu"}
    int8_line = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
                 "value": 9000.0, "backend": "tpu", "precision": "int8"}
    base = _baselines(extra=[cpu_line, int8_line] * 3)
    key_tpu = "knn_qps_sift1m_n1000000_d128_k100|tpu|default"
    # the CPU/int8 lines landed under their OWN keys, leaving the tpu
    # f32-family baseline untouched
    assert base[key_tpu]["value"]["n"] == len(HISTORY)
    assert "knn_qps_sift1m_n1000000_d128_k100|cpu|default" in base
    assert "knn_qps_sift1m_n1000000_d128_k100|tpu|int8" in base
    # and a cpu line is judged against the cpu baseline, never the tpu
    v = sentinel.verdict_for_line(dict(cpu_line), baselines=base)
    assert v["baseline_key"].endswith("|cpu|default")
    assert v["fields"]["value"]["verdict"] == "ok"


def test_short_history_yields_no_baseline():
    base = sentinel.build_baselines(HISTORY[:2])
    assert base == {}
    v = sentinel.verdict_for_line(
        {"metric": "knn_qps_other", "backend": "tpu", "value": 1.0},
        baselines=_baselines())
    assert v["verdict"] == "no_baseline"


def test_iter_history_reads_real_repo_artifacts():
    records = list(sentinel.iter_history_lines(REPO))
    assert any(r.get("metric", "").startswith("knn_qps_sift1m")
               for r in records)
    # max_round excludes the round being judged
    bounded = list(sentinel.iter_history_lines(REPO, max_round=4))
    assert all(sentinel._file_round(r["_source"]) < 4 for r in bounded)
    # the real history builds baselines without raising
    sentinel.build_baselines(records)


def _write_history(tmp_path, rounds):
    for rnd, lines in rounds.items():
        p = tmp_path / f"TPU_BENCH_r{rnd:02d}.jsonl"
        p.write_text("".join(json.dumps(ln) + "\n" for ln in lines))


def test_perf_sentinel_cli_lint_and_strict_gate(tmp_path):
    # rounds 1-4: the tight history; round 5: a 20% regression
    _write_history(tmp_path, {
        i + 1: [HISTORY[i]] for i in range(4)})
    _write_history(tmp_path, {5: [
        {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 4800.0,
         "backend": "tpu", "measured_round": 5,
         "measured_at_commit": "eee5555"}]})
    script = f"{REPO}/scripts/perf_sentinel.py"
    r = subprocess.run(
        [sys.executable, script, "--repo", str(tmp_path), "--lint"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    # warn-only by default: verdict printed, exit 0
    r = subprocess.run(
        [sys.executable, script, "--repo", str(tmp_path),
         "--check-latest"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "regress" in r.stdout
    # --strict turns the regress verdict into a hard failure
    r = subprocess.run(
        [sys.executable, script, "--repo", str(tmp_path),
         "--check-latest", "--strict"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    # a healthy latest round passes strict
    _write_history(tmp_path, {5: [
        {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 6050.0,
         "backend": "tpu", "measured_round": 5,
         "measured_at_commit": "eee5555"}]})
    r = subprocess.run(
        [sys.executable, script, "--repo", str(tmp_path),
         "--check-latest", "--strict"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_refresher_stamps_sentinel_verdicts(tmp_path):
    import shutil

    # a self-contained repo dir: history rounds 1-4 + this round's
    # session lines, with the refresher copied alongside (it resolves
    # paths relative to its own location)
    scripts_dir = tmp_path / "scripts"
    scripts_dir.mkdir()
    shutil.copy(f"{REPO}/scripts/refresh_bench_artifacts.py",
                scripts_dir / "refresh_bench_artifacts.py")
    (tmp_path / "knn_tpu").symlink_to(f"{REPO}/knn_tpu")
    _write_history(tmp_path, {i + 1: [HISTORY[i]] for i in range(4)})
    (tmp_path / "tpu_bench_lines.jsonl").write_text(json.dumps(
        {"metric": "knn_qps_sift1m_n1000000_d128_k100", "value": 4700.0,
         "backend": "tpu", "pallas_gate_ok": True,
         "measured_at_commit": "fff6666"}) + "\n")
    r = subprocess.run(
        [sys.executable, str(scripts_dir / "refresh_bench_artifacts.py"),
         "5"],
        capture_output=True, text=True, timeout=120, cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    out = [json.loads(ln) for ln in
           (tmp_path / "TPU_BENCH_r05.jsonl").read_text().splitlines()]
    rec = next(x for x in out
               if x["metric"] == "knn_qps_sift1m_n1000000_d128_k100")
    # fresh line (21% below the tight baseline) carries its verdict
    assert rec["sentinel"]["verdict"] == "regress"
    assert "sentinel=regress" in r.stdout


def test_bench_line_sentinel_block_shape():
    # the block bench.py embeds: verdict + per-field classifications
    v = sentinel.verdict_for_line(
        {"metric": "knn_qps_sift1m_n1000000_d128_k100",
         "backend": "tpu", "value": 6000.0, "mfu": 0.030,
         "device_phase_qps": 24000.0},
        baselines=_baselines())
    assert v["verdict"] == "ok"
    assert set(v["fields"]) == {"value", "mfu", "device_phase_qps"}
    for f in v["fields"].values():
        assert f["verdict"] == "ok"
        assert {"baseline_median", "baseline_n", "drop_rel",
                "ok_bar", "regress_bar"} <= set(f)
