"""The load-generation harness (knn_tpu.loadgen): deterministic seeded
arrivals, bursty on/off structure, JSONL trace round-trip, the
open-loop property (arrivals never gated by completions), the bounded
result log, and knee detection against the synthetic latency model —
all device-free by construction (the package imports no JAX)."""

import sys

import numpy as np
import pytest

from knn_tpu import loadgen
from knn_tpu.loadgen import (
    Request,
    SyntheticTarget,
    TenantSpec,
    WorkloadSpec,
    generate,
    knee_sweep,
    load_trace,
    parse_tenants,
    rates_around,
    run_workload,
    save_trace,
    validate_knee_block,
)

POOL = np.zeros((64, 8), np.float32)


def test_loadgen_package_is_jax_free():
    # generating/replaying traces must not require the accelerator
    # stack; the suite's own conftest imports JAX, so prove it in a
    # clean interpreter
    import subprocess

    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; import knn_tpu.loadgen; "
         "assert 'jax' not in sys.modules, 'loadgen imported jax'"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


# -- deterministic arrivals -----------------------------------------------
def test_poisson_arrivals_deterministic_under_seed():
    spec = WorkloadSpec(rate_qps=300, duration_s=0.5, seed=11,
                        tenants=(TenantSpec("a", weight=2),
                                 TenantSpec("b", weight=1)))
    r1, r2 = generate(spec), generate(spec)
    assert r1 == r2  # element for element
    # a different seed is a different trace
    r3 = generate(WorkloadSpec(rate_qps=300, duration_s=0.5, seed=12,
                               tenants=spec.tenants))
    assert r1 != r3
    # schedule sanity: ascending offsets inside the duration, count in
    # the right ballpark for the rate (Poisson: loose 3-sigma-ish band)
    ts = [r.t for r in r1]
    assert ts == sorted(ts)
    assert all(0 < t < 0.5 for t in ts)
    assert 90 <= len(r1) <= 220  # mean 150

def test_tenant_mix_weights_shapes_and_tags():
    spec = WorkloadSpec(
        rate_qps=800, duration_s=1.0, seed=0,
        tenants=(TenantSpec("gold", weight=3, batch_sizes=(2, 4),
                            deadline_ms=50.0, priority=0),
                 TenantSpec("free", weight=1, batch_sizes=(1,),
                            priority=5)))
    reqs = generate(spec)
    gold = [r for r in reqs if r.tenant == "gold"]
    free = [r for r in reqs if r.tenant == "free"]
    assert len(gold) + len(free) == len(reqs)
    # 3:1 weights, loose band
    assert 0.6 < len(gold) / len(reqs) < 0.9
    assert all(r.rows in (2, 4) for r in gold)
    assert all(r.rows == 1 for r in free)
    assert all(r.deadline_ms == 50.0 and r.priority == 0 for r in gold)
    assert all(r.deadline_ms is None and r.priority == 5 for r in free)


def test_onoff_bursty_arrivals_respect_off_windows():
    spec = WorkloadSpec(rate_qps=200, duration_s=2.0, seed=4,
                        arrival="onoff", on_s=0.2, off_s=0.3, burst=3.0)
    reqs = generate(spec)
    assert reqs == generate(spec)  # still deterministic
    period = 0.5
    phases = np.asarray([r.t % period for r in reqs])
    assert (phases <= 0.2 + 1e-9).all()  # silence in every off window
    assert len(reqs) > 50
    # LOW-rate regime: re-drawn gaps regularly overshoot the next
    # on-window (e^{-rate*on} is large), so the invariant needs the
    # looped skip, not a single one — sweep several seeds
    for seed in range(5):
        low = WorkloadSpec(rate_qps=4, duration_s=30.0, seed=seed,
                           arrival="onoff", on_s=0.25, off_s=0.25,
                           burst=2.0)
        ph = np.asarray([r.t % 0.5 for r in generate(low)])
        assert ph.size and (ph <= 0.25 + 1e-9).all()


def test_workload_validation_rejects_bad_specs():
    with pytest.raises(ValueError, match="rate_qps"):
        generate(WorkloadSpec(rate_qps=0))
    with pytest.raises(ValueError, match="arrival"):
        generate(WorkloadSpec(arrival="nope"))
    with pytest.raises(ValueError, match="duplicate"):
        generate(WorkloadSpec(tenants=(TenantSpec("a"), TenantSpec("a"))))
    with pytest.raises(ValueError, match="weight"):
        generate(WorkloadSpec(tenants=(TenantSpec("a", weight=0),)))
    with pytest.raises(ValueError, match="trace_path"):
        generate(WorkloadSpec(arrival="replay"))
    with pytest.raises(ValueError, match="batch_sizes"):
        TenantSpec("a", batch_sizes=()).validate()


def test_parse_tenants_shorthand():
    ts = parse_tenants("gold:3:0,free:1:2,plain")
    assert [(t.name, t.weight, t.priority) for t in ts] == [
        ("gold", 3.0, 0), ("free", 1.0, 2), ("plain", 1.0, 0)]
    with pytest.raises(ValueError):
        parse_tenants("")


# -- trace persistence ----------------------------------------------------
def test_trace_replay_round_trip(tmp_path):
    spec = WorkloadSpec(rate_qps=250, duration_s=0.4, seed=3,
                        tenants=(TenantSpec("a", deadline_ms=20.0),
                                 TenantSpec("b", precision="int8")))
    reqs = generate(spec)
    path = str(tmp_path / "trace.jsonl")
    save_trace(reqs, path)
    loaded = load_trace(path)
    assert loaded == sorted(reqs, key=lambda r: r.t)
    # the replay arrival process reads the same schedule back
    replayed = generate(WorkloadSpec(arrival="replay", trace_path=path))
    assert replayed == loaded
    # malformed lines are a loud error, never a silent skip
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"tenant": "a"}\n')  # missing fields
    with pytest.raises(ValueError, match="not a request record"):
        load_trace(str(bad))
    bad.write_text("not json\n")
    with pytest.raises(ValueError, match="not JSON"):
        load_trace(str(bad))


# -- the open-loop driver -------------------------------------------------
def test_open_loop_arrivals_not_gated_by_completions():
    """The defining property: against a server 10x slower than the
    offered rate, every request is still SUBMITTED on schedule — a
    closed-loop driver would collapse to the server's pace."""
    spec = WorkloadSpec(rate_qps=150, duration_s=0.4, seed=5,
                        tenants=(TenantSpec("a", batch_sizes=(1,)),))
    reqs = generate(spec)
    with SyntheticTarget(15.0) as target:  # ~10x too slow
        rep = run_workload(target, reqs, queries=POOL,
                           include_records=True)
    assert rep["offered"] == len(reqs)
    assert rep["ok"] == len(reqs)  # eventually all complete
    # submissions tracked the schedule, not the completions: every
    # arrival landed within a small slack of its scheduled time even
    # though service lagged seconds behind
    drift = [r["arrival_s"] - r["scheduled_s"] for r in rep["records"]]
    assert max(drift) < 0.25
    # and completions genuinely lagged (the server was the bottleneck)
    assert rep["wall_s"] > 3 * 0.4


def test_result_log_bounded_but_counts_complete():
    spec = WorkloadSpec(rate_qps=400, duration_s=0.25, seed=6,
                        tenants=(TenantSpec("a", batch_sizes=(1,)),))
    reqs = generate(spec)
    with SyntheticTarget(2000.0) as target:
        rep = run_workload(target, reqs, queries=POOL, log_cap=8,
                           include_records=True)
    assert rep["offered"] == len(reqs)
    assert rep["ok"] == len(reqs)  # aggregate truth is complete
    assert rep["records_kept"] == 8  # detail is bounded
    assert rep["records_dropped"] == len(reqs) - 8
    assert len(rep["records"]) == 8


def test_driver_records_explicit_outcomes_and_per_tenant():
    spec = WorkloadSpec(
        rate_qps=500, duration_s=0.3, seed=7,
        tenants=(TenantSpec("a", weight=1, batch_sizes=(1,)),
                 TenantSpec("b", weight=1, batch_sizes=(1,))))
    reqs = generate(spec)
    # a tiny bounded synthetic queue: overload MUST produce explicit
    # queue_full rejections, recorded per tenant
    with SyntheticTarget(50.0, max_depth=4) as target:
        rep = run_workload(target, reqs, queries=POOL)
    assert rep["offered"] == len(reqs)
    assert rep["rejected"] > 0
    assert rep["outcomes"].get("rejected:queue_full", 0) == rep["rejected"]
    assert rep["ok"] + rep["rejected"] + rep["shed"] + rep["errors"] \
        == rep["offered"]
    for tenant in ("a", "b"):
        t = rep["per_tenant"][tenant]
        assert t["offered"] == sum(t["outcomes"].values())
    assert rep["shed_fraction"] == pytest.approx(
        (rep["offered"] - rep["ok"]) / rep["offered"], abs=1e-3)


def test_dispatch_time_recorded_from_target():
    spec = WorkloadSpec(rate_qps=100, duration_s=0.2, seed=8,
                        tenants=(TenantSpec("a", batch_sizes=(1,)),))
    with SyntheticTarget(500.0) as target:
        rep = run_workload(target, generate(spec), queries=POOL,
                           include_records=True)
    ok = [r for r in rep["records"] if r["outcome"] == "ok"]
    assert ok
    for r in ok:
        # (tenant, arrival, deadline, dispatch, completion, outcome):
        # the full per-request record the driver promises
        assert r["dispatch_s"] is not None
        assert r["arrival_s"] <= r["dispatch_s"] <= r["completion_s"]


# -- knee detection -------------------------------------------------------
def test_knee_detected_on_synthetic_latency_model():
    """The detector must find the knee of a server whose knee is known
    by construction: capacity C, latency near one service time below
    C, queue-growth blowup above it."""
    cap = 250.0
    base = WorkloadSpec(rate_qps=1.0, duration_s=0.5, seed=9,
                        tenants=(TenantSpec("a", batch_sizes=(1,)),))
    rates = [0.3 * cap, 0.6 * cap, 2 * cap, 4 * cap]
    block = knee_sweep(lambda: SyntheticTarget(cap), base, rates,
                       queries=POOL, slo_p99_ms=8 * 1e3 / cap)
    assert validate_knee_block(block) == []
    assert block["knee_qps"] is not None
    # the knee sits below capacity and well below the saturated steps
    assert 0.15 * cap <= block["knee_qps"] <= 1.1 * cap
    assert block["knee_rate_qps"] in rates
    # the saturated steps are flagged over-SLO
    top = block["rate_steps"][-1]
    assert top["within_slo"] is False
    assert top["admitted_p99_ms"] > 8 * 1e3 / cap


def test_knee_sweep_tolerates_zero_arrival_steps():
    """A low step whose Poisson draw produces no arrivals must record
    an empty step, not abort the sweep and lose the higher steps."""
    base = WorkloadSpec(rate_qps=1.0, duration_s=0.2, seed=0,
                        tenants=(TenantSpec("a", batch_sizes=(1,)),))
    assert generate(base.at_rate(0.1)) == []  # the empty step, pinned
    block = knee_sweep(lambda: SyntheticTarget(500.0), base,
                       [0.1, 100.0], queries=POOL, slo_p99_ms=100.0)
    assert validate_knee_block(block) == []
    first, second = block["rate_steps"]
    assert first["empty_schedule"] is True and first["offered"] == 0
    assert first["within_slo"] is False
    assert second["ok"] > 0
    assert block["knee_qps"] == second["achieved_qps"]


def test_validate_knee_block_refuses_malformation():
    assert validate_knee_block("nope") != []
    assert validate_knee_block({"version": 99}) != []
    ok_block = {
        "version": 1, "slo_p99_ms": 50.0,
        "rate_steps": [{"rate_qps": 10.0, "offered": 5, "ok": 5,
                        "achieved_qps": 9.0, "shed_fraction": 0.0,
                        "within_slo": True}],
        "knee_qps": 9.0, "knee_rate_qps": 10.0}
    assert validate_knee_block(ok_block) == []
    bad = dict(ok_block, rate_steps=[{"rate_qps": 10.0}])
    assert any("missing" in e for e in validate_knee_block(bad))
    bad = dict(ok_block, slo_p99_ms=-1)
    assert any("slo_p99_ms" in e for e in validate_knee_block(bad))
    # knee claimed but no step within SLO -> inconsistent
    bad = dict(ok_block, rate_steps=[dict(ok_block["rate_steps"][0],
                                          within_slo=False)])
    assert any("within_slo" in e for e in validate_knee_block(bad))
    # a block that recorded its own failure is exempt (honest error
    # fields curate; fabricated numbers do not)
    assert validate_knee_block({"error": "boom"}) == []


def test_rates_around_brackets_anchor():
    rates = rates_around(100.0)
    assert rates[0] < 100.0 < rates[-1]
    assert rates == sorted(rates)
    with pytest.raises(ValueError):
        rates_around(0)


def test_sentinel_curates_knee_qps():
    """knee_qps is a curated sentinel field: read top-level or out of
    the loadgen_knee block, baselined like-for-like, regressions
    flagged."""
    from knn_tpu.obs import sentinel

    assert ("knee_qps", "higher") in sentinel.CURATED_FIELDS
    rec = {"metric": "m", "backend": "tpu",
           "loadgen_knee": {"knee_qps": 123.0}}
    assert sentinel.curated_value(rec, "knee_qps") == 123.0
    assert sentinel.curated_value({"knee_qps": 7.0}, "knee_qps") == 7.0
    history = [
        {"metric": "m", "backend": "tpu", "value": 1.0, "knee_qps": 100.0,
         "measured_at_commit": f"c{i}", "measured_round": i}
        for i in range(4)
    ]
    baselines = sentinel.build_baselines(history)
    fresh = {"metric": "m", "backend": "tpu", "value": 1.0,
             "knee_qps": 50.0}
    verdict = sentinel.verdict_for_line(fresh, baselines=baselines)
    assert verdict["fields"]["knee_qps"]["verdict"] == "regress"
    good = {"metric": "m", "backend": "tpu", "value": 1.0,
            "knee_qps": 99.0}
    verdict = sentinel.verdict_for_line(good, baselines=baselines)
    assert verdict["fields"]["knee_qps"]["verdict"] == "ok"


# -- write-stream mix (knn_tpu.index satellite) ---------------------------
def test_write_mix_deterministic_and_replayable(tmp_path):
    spec = WorkloadSpec(
        rate_qps=400, duration_s=0.5, seed=3,
        tenants=(TenantSpec("r", weight=0.7, batch_sizes=(1, 2)),
                 TenantSpec("w", weight=0.3, batch_sizes=(1,),
                            insert_fraction=0.5, delete_fraction=0.25,
                            write_rows=2)))
    a, b = generate(spec), generate(spec)
    assert a == b  # element-for-element, kinds included
    kinds = {k: sum(1 for r in a if r.kind == k)
             for k in ("query", "insert", "delete")}
    assert kinds["insert"] > 0 and kinds["delete"] > 0
    assert all(r.rows == 2 for r in a if r.kind == "insert")
    assert all(r.rows == 1 for r in a if r.kind == "delete")
    assert all(r.kind == "query" for r in a if r.tenant == "r")
    # JSONL round-trip keeps the kind; old-style records (no kind
    # field) load as pure-query schedules
    p = tmp_path / "t.jsonl"
    save_trace(a, str(p))
    assert load_trace(str(p)) == a
    p2 = tmp_path / "old.jsonl"
    p2.write_text('{"tenant": "x", "t": 0.1, "rows": 2}\n')
    (old,) = load_trace(str(p2))
    assert old.kind == "query"


def test_write_free_schedule_unchanged_by_the_kind_draw():
    # the kind draw happens ONLY for write-mixed tenants, so a
    # write-free spec's rng sequence — and therefore its schedule — is
    # the PRE-write-stream one, draw for draw.  Pinned by replaying
    # the generator's exact draw protocol with NO kind draw: if the
    # draw ever moves outside the write-mix guard, every recorded
    # write-free trace stops replaying deterministically.
    spec = WorkloadSpec(rate_qps=300, duration_s=0.4, seed=9,
                        tenants=(TenantSpec("a", batch_sizes=(1, 4)),
                                 TenantSpec("b", weight=2.0,
                                            batch_sizes=(2,))))
    got = generate(spec)
    assert all(r.kind == "query" for r in got)
    from knn_tpu.loadgen.workload import _arrival_times

    rng = np.random.default_rng(spec.seed)
    times = _arrival_times(spec, rng)
    weights = np.asarray([t.weight for t in spec.tenants], np.float64)
    picks = rng.choice(2, size=len(times), p=weights / weights.sum())
    expect = []
    for t, pick in zip(times, picks):
        ten = spec.tenants[int(pick)]
        rows = int(ten.batch_sizes[int(
            rng.integers(0, len(ten.batch_sizes)))])
        expect.append((ten.name, round(float(t), 6), rows))
    assert [(r.tenant, r.t, r.rows) for r in got] == expect


def test_write_mix_validation():
    with pytest.raises(ValueError, match="fractions"):
        TenantSpec("w", insert_fraction=0.8,
                   delete_fraction=0.3).validate()
    with pytest.raises(ValueError, match="fractions"):
        TenantSpec("w", insert_fraction=-0.1).validate()
    with pytest.raises(ValueError, match="write_rows"):
        TenantSpec("w", insert_fraction=0.1, write_rows=0).validate()


def test_driver_write_stream_against_synthetic():
    spec = WorkloadSpec(
        rate_qps=500, duration_s=0.4, seed=5,
        tenants=(TenantSpec("r", weight=0.6, batch_sizes=(1,)),
                 TenantSpec("w", weight=0.4, batch_sizes=(1,),
                            insert_fraction=0.5,
                            delete_fraction=0.25)))
    reqs = generate(spec)
    n_writes = sum(1 for r in reqs if r.kind != "query")
    assert n_writes > 0
    with SyntheticTarget(2000.0) as tgt:
        rep = run_workload(tgt, reqs, queries=POOL)
    # report: write counts live apart from the read-side numbers
    w = rep["writes"]
    assert w["total"] == n_writes
    assert w["insert"].get("ok", 0) == tgt.writes.get("insert", 0) > 0
    # deletes can only target confirmed inserts; early ones skip loudly
    n_del = sum(1 for r in reqs if r.kind == "delete")
    del_outcomes = w.get("delete", {})
    assert sum(del_outcomes.values()) == n_del
    # read-side numbers cover QUERIES only
    assert rep["offered"] == len(reqs) - n_writes
    assert rep["ok"] <= rep["offered"]
    lat = rep["latency_ms"]
    assert lat is None or lat["count"] <= rep["ok"]


def test_driver_refuses_writes_against_writeless_target():
    class NoWrites:
        def submit(self, *a, **k):  # pragma: no cover - never reached
            raise AssertionError

    spec = WorkloadSpec(
        rate_qps=200, duration_s=0.2, seed=1,
        tenants=(TenantSpec("w", batch_sizes=(1,),
                            insert_fraction=1.0),))
    with pytest.raises(ValueError, match="submit_write"):
        run_workload(NoWrites(), generate(spec), queries=POOL)


def test_sentinel_curates_mutation_admitted_p99():
    from knn_tpu.obs import sentinel

    assert ("mutation_admitted_p99_ms", "lower") \
        in sentinel.CURATED_FIELDS
    rec = {"metric": "m", "backend": "tpu",
           "mutation": {"admitted_p99_ms": 12.5}}
    assert sentinel.curated_value(rec, "mutation_admitted_p99_ms") \
        == 12.5
    history = [
        {"metric": "m", "backend": "tpu", "value": 1.0,
         "mutation_admitted_p99_ms": 10.0,
         "measured_at_commit": f"c{i}", "measured_round": i}
        for i in range(4)
    ]
    baselines = sentinel.build_baselines(history)
    # lower is better: a p99 that DOUBLES regresses, one that halves
    # reads ok
    worse = {"metric": "m", "backend": "tpu", "value": 1.0,
             "mutation_admitted_p99_ms": 25.0}
    assert sentinel.verdict_for_line(worse, baselines=baselines)[
        "fields"]["mutation_admitted_p99_ms"]["verdict"] == "regress"
    better = {"metric": "m", "backend": "tpu", "value": 1.0,
              "mutation_admitted_p99_ms": 9.5}
    assert sentinel.verdict_for_line(better, baselines=baselines)[
        "fields"]["mutation_admitted_p99_ms"]["verdict"] == "ok"


# -- offline bulk-join lane (bulk kNN-join satellite) ---------------------
def test_bulk_mix_deterministic_and_shaped():
    spec = WorkloadSpec(
        rate_qps=400, duration_s=0.5, seed=11,
        tenants=(TenantSpec("serve", weight=3, batch_sizes=(1, 2)),
                 TenantSpec("joiner", weight=1, batch_sizes=(1,),
                            bulk_fraction=0.6, bulk_rows=32)))
    a, b = generate(spec), generate(spec)
    assert a == b  # element for element, kinds included
    n_bulk = sum(1 for r in a if r.kind == "bulk")
    assert n_bulk > 0
    assert all(r.rows == 32 for r in a if r.kind == "bulk")
    assert all(r.kind == "query" for r in a if r.tenant == "serve")


def test_bulk_free_schedule_unchanged_by_the_bulk_draw():
    # the kind draw stays gated on MIXED tenants: adding bulk_fraction
    # to the gate must not move the rng sequence of a pure-query spec
    # (same pin as the write-free case — recorded traces keep replaying)
    spec = WorkloadSpec(rate_qps=300, duration_s=0.4, seed=9,
                        tenants=(TenantSpec("a", batch_sizes=(1, 4)),
                                 TenantSpec("b", weight=2.0,
                                            batch_sizes=(2,))))
    got = generate(spec)
    assert all(r.kind == "query" for r in got)
    assert all(r.bulk_fraction == 0.0 for r in spec.tenants)


def test_bulk_validation():
    with pytest.raises(ValueError, match="fractions"):
        TenantSpec("j", insert_fraction=0.5, delete_fraction=0.3,
                   bulk_fraction=0.3).validate()
    with pytest.raises(ValueError, match="fractions"):
        TenantSpec("j", bulk_fraction=-0.1).validate()
    with pytest.raises(ValueError, match="bulk_rows"):
        TenantSpec("j", bulk_fraction=0.1, bulk_rows=0).validate()


def test_driver_bulk_lane_has_own_section_and_reads_stay_clean():
    """The mixed knee shape: bulk superblocks ride target.submit (the
    same admission control as queries), but their outcomes + latencies
    land in the report's ``bulk`` section — the interactive read-side
    offered/percentiles cover queries ONLY."""
    spec = WorkloadSpec(
        rate_qps=500, duration_s=0.4, seed=4,
        tenants=(TenantSpec("serve", weight=0.7, batch_sizes=(1,)),
                 TenantSpec("joiner", weight=0.3, batch_sizes=(1,),
                            bulk_fraction=0.8, bulk_rows=16)))
    reqs = generate(spec)
    n_bulk = sum(1 for r in reqs if r.kind == "bulk")
    assert n_bulk > 0
    with SyntheticTarget(2000.0) as tgt:
        rep = run_workload(tgt, reqs, queries=POOL)
    bulk = rep["bulk"]
    assert bulk["total"] == sum(bulk["outcomes"].values()) == n_bulk
    assert bulk["ok"] <= bulk["total"]
    if bulk["ok"]:
        assert bulk["latency_ms"]["count"] == bulk["ok"]
    # read-side numbers cover queries only — no dilution either way
    assert rep["offered"] == len(reqs) - n_bulk
    assert rep["ok"] <= rep["offered"]
    lat = rep["latency_ms"]
    assert lat is None or lat["count"] <= rep["ok"]
    # bulk never requires submit_write: a write-less target serves it
    assert "writes" not in rep
