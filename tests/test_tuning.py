"""The persistent autotuner (knn_tpu.tuning): winner persistence and
reload round-trips, cache-key mismatches fall back to defaults, the
bitwise gate keeps broken candidates from ever winning, explicit
pallas_knobs beat the cache, and a warm cache resolves with ZERO
re-timing (pinned via the module counters — the same evidence
`python -m knn_tpu.cli tune` prints)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import importlib

from knn_tpu import tuning

# the module object (the package re-exports the autotune FUNCTION under
# the same name, so attribute access would shadow it)
autotune_mod = importlib.import_module("knn_tpu.tuning.autotune")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def data(rng):
    db = rng.normal(size=(700, 16)).astype(np.float32) * 10
    q = rng.normal(size=(9, 16)).astype(np.float32) * 10
    return db, q


@pytest.fixture
def cache_path(tmp_path):
    return str(tmp_path / "autotune.json")


def test_winner_persistence_and_reload_roundtrip(data, cache_path):
    db, q = data
    tuning.reset_counters()
    entry = tuning.autotune(db, q, 5, margin=8, grid_level="quick", runs=1,
                            cache_path=cache_path)
    assert entry["cached"] is False
    assert tuning.counters()["candidates_timed"] >= 3
    assert os.path.exists(cache_path)
    # the file is the documented format and reloads to the same winner
    raw = json.load(open(cache_path))
    assert raw["version"] == 1
    (key,) = raw["entries"]
    assert key == tuning.cache_key("cpu", 700, 16, 5, "l2", None)
    reloaded = tuning.TuneCache(cache_path).get(key)
    assert reloaded["knobs"] == entry["knobs"]
    assert reloaded["winner_ms"] == entry["winner_ms"]
    # resolve() for the same shape returns the persisted winner
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "cache"
    assert knobs == {**tuning.DEFAULT_KNOBS, **entry["knobs"]}


def test_warm_cache_zero_retiming(data, cache_path):
    db, q = data
    tuning.autotune(db, q, 5, margin=8, grid_level="quick", runs=1,
                    cache_path=cache_path)
    tuning.reset_counters()
    entry = tuning.autotune(db, q, 5, margin=8, grid_level="quick", runs=1,
                            cache_path=cache_path)
    assert entry["cached"] is True
    c = tuning.counters()
    assert c["candidates_timed"] == 0  # ZERO re-timing on a warm cache
    assert c["tune_searches"] == 0
    assert c["cache_hits"] == 1


def test_cache_key_mismatch_falls_back_to_defaults(data, cache_path):
    db, q = data
    tuning.autotune(db, q, 5, margin=8, grid_level="quick", runs=1,
                    cache_path=cache_path)
    # ANY key field mismatch must miss: different k, n, d, metric, dtype,
    # device kind — a winner tuned for one shape says nothing elsewhere
    for kwargs in (
        dict(n=700, d=16, k=7),                       # k differs
        dict(n=701, d=16, k=5),                       # n differs
        dict(n=700, d=32, k=5),                       # d differs
        dict(n=700, d=16, k=5, metric="cosine"),      # metric differs
        dict(n=700, d=16, k=5, dtype="bfloat16"),     # dtype differs
        dict(n=700, d=16, k=5, device_kind="TPU v5e"),  # device differs
    ):
        n = kwargs.pop("n")
        d = kwargs.pop("d")
        k = kwargs.pop("k")
        knobs, info = tuning.resolve_full(n, d, k, cache_path=cache_path,
                                          **kwargs)
        assert info["source"] == "default", kwargs
        assert knobs == tuning.DEFAULT_KNOBS


def test_gate_failed_candidate_can_never_win(data, cache_path, monkeypatch):
    db, q = data
    real_search = autotune_mod._search_once

    def corrupt_streaming(queries, dbx, k, margin, knobs):
        d, i = real_search(queries, dbx, k, margin, knobs)
        if knobs["kernel"] == "streaming":
            i = np.array(i)
            i[0, 0] = (i[0, 0] + 1) % dbx.shape[0]  # one wrong neighbor
        return d, i

    monkeypatch.setattr(autotune_mod, "_search_once", corrupt_streaming)
    tuning.reset_counters()
    entry = tuning.autotune(db, q, 5, margin=8, grid_level="quick", runs=1,
                            cache_path=cache_path)
    # the corrupted candidate is recorded ineligible (never timed) and
    # cannot be selected no matter how fast it would have been
    assert entry["timings_ms"]["kernel=streaming"] is None
    assert "bitwise gate" in entry["errors"]["kernel=streaming"]
    assert entry["knobs"]["kernel"] != "streaming"
    assert tuning.counters()["candidates_gated_out"] >= 1
    # and the persisted winner keeps the poison out of later resolves
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "cache"
    assert knobs["kernel"] != "streaming"


def test_explicit_knobs_beat_cache(data, cache_path, rng):
    db, q = data
    # seed the cache with a NON-default winner so the override direction
    # is unambiguous
    key = tuning.cache_key("cpu", 700, 16, 5, "l2", None)
    tuning.TuneCache(cache_path).put(key, {
        "knobs": {**tuning.DEFAULT_KNOBS, "kernel": "streaming",
                  "tile_n": 256},
        "winner_ms": 1.0,
    })
    knobs, info = tuning.resolve_full(
        700, 16, 5, cache_path=cache_path,
        overrides={"kernel": "tiled", "block_q": 16})
    assert info["source"] == "cache"
    assert knobs["kernel"] == "tiled"      # override beat the cache
    assert knobs["tile_n"] == 256          # un-overridden cache knob kept
    assert knobs["block_q"] == 16
    assert info["overridden"] == ["block_q", "kernel"]

    # end to end through ShardedKNN.search_certified: explicit args win,
    # un-overridden knobs come from the cache, and the stats record both
    from knn_tpu.parallel import ShardedKNN, make_mesh

    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=5)
    _, i_cache, st = prog.search_certified(
        q, selector="pallas", margin=8, tune_cache=cache_path)
    assert st["tuning"]["source"] == "cache"
    assert st["pallas_knobs"]["kernel"] == "streaming"  # cache winner ran
    assert st["pallas_knobs"]["tile_n"] == 256
    _, i_over, st2 = prog.search_certified(
        q, selector="pallas", margin=8, tune_cache=cache_path,
        kernel="tiled", tile_n=384)
    assert st2["pallas_knobs"]["kernel"] == "tiled"
    assert st2["pallas_knobs"]["tile_n"] == 384
    assert set(st2["tuning"]["overridden"]) == {"kernel", "tile_n"}
    # exactness is knob-independent (the certified contract)
    np.testing.assert_array_equal(i_cache, i_over)


def test_resolve_rejects_unknown_knob():
    with pytest.raises(ValueError, match="unknown pallas knob"):
        tuning.resolve(100, 8, 3, overrides={"warp_speed": 9})


def test_corrupt_cache_degrades_to_defaults(cache_path):
    with open(cache_path, "w") as f:
        f.write("{not json")
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "default"
    assert knobs == tuning.DEFAULT_KNOBS


def test_cli_tune_roundtrip_zero_retiming(tmp_path):
    """The acceptance path verbatim: `python -m knn_tpu.cli tune` on CPU
    persists a cache file; a second run resolves from it with zero
    re-timing, asserted via the counters in the CLI's JSON output."""
    cache = str(tmp_path / "cli_tune.json")
    args = [sys.executable, "-m", "knn_tpu.cli", "tune", "--n", "600",
            "--dim", "8", "--k", "3", "--queries", "8", "--margin", "4",
            "--grid", "quick", "--runs", "1", "--cache", cache]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)

    def run():
        r = subprocess.run(args, capture_output=True, text=True, env=env,
                           timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    first = run()
    assert first["cached"] is False
    assert first["counters"]["candidates_timed"] >= 3
    assert os.path.exists(cache)
    second = run()
    assert second["cached"] is True
    assert second["counters"]["candidates_timed"] == 0
    assert second["counters"]["tune_searches"] == 0
    assert second["knobs"] == first["knobs"]


def test_cache_key_carries_kernel_version_token():
    from knn_tpu.ops.pallas_knn import KERNEL_VERSION

    key = tuning.cache_key("cpu", 700, 16, 5, "l2", None)
    assert key.endswith(f"|kv{KERNEL_VERSION}")


def test_stale_kernel_version_entry_falls_back_to_defaults(cache_path):
    """A persisted winner keyed for an OLDER kernel build (different —
    or missing — kv token) must miss: winners are measurements of one
    kernel's code, and a changed kernel invalidates them."""
    key = tuning.cache_key("cpu", 700, 16, 5, "l2", None)
    base = key.rsplit("|kv", 1)[0]
    cache = tuning.TuneCache(cache_path)
    # pre-token entry (the old key format) AND a wrong-version entry
    cache.put(base, {"knobs": {**tuning.DEFAULT_KNOBS,
                               "kernel": "streaming"}})
    cache.put(base + "|kv-stale", {"knobs": {**tuning.DEFAULT_KNOBS,
                                             "tile_n": 256}})
    # ... and a KERNEL_VERSION-4 entry carrying a sub-int8 winner: the
    # 4 -> 5 bump (the int4/pq arms changed the kernel) must invalidate
    # it even though "precision": "int4" is a perfectly current knob
    from knn_tpu.ops.pallas_knn import KERNEL_VERSION

    assert KERNEL_VERSION == 5
    cache.put(base + "|kv4", {"knobs": {**tuning.DEFAULT_KNOBS,
                                        "precision": "int4",
                                        "kernel": "streaming"}})
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "default"
    assert knobs == tuning.DEFAULT_KNOBS
    # a current-version entry under the same shape DOES hit
    cache.put(key, {"knobs": {**tuning.DEFAULT_KNOBS, "block_q": 16}})
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "cache"
    assert knobs["block_q"] == 16


def test_standard_grid_includes_int8_candidate():
    grid = tuning.knob_grid("standard")
    assert any(c["precision"] == "int8" for c in grid)
    # quick stays int8-free (CPU-interpret friendly minimal set)
    assert all(c["precision"] != "int8" for c in tuning.knob_grid("quick"))
    # full covers int8 x streaming (the HBM-bound cross)
    assert any(c["precision"] == "int8" and c["kernel"] == "streaming"
               for c in tuning.knob_grid("full"))


def test_grid_covers_sub_int8_arms_and_refuses_pq_fused():
    """The compressed tiers enter the grid where the roofline says
    they pay: int4 x streaming (the headline hbm_bound attack) and
    both pq db-streaming strategies sit in standard; full adds the
    int4 x fused cross.  pq x fused appears at NO level — the kernel
    refuses it (carry soundness unproven for reconstruction-space
    scores), so a grid that emitted it would crash the tuner."""
    std = tuning.knob_grid("standard")
    assert any(c["precision"] == "int4" and c["kernel"] == "streaming"
               for c in std)
    assert any(c["precision"] == "pq" and c["kernel"] == "streaming"
               for c in std)
    assert any(c["precision"] == "pq" and c["kernel"] == "tiled"
               for c in std)
    full = tuning.knob_grid("full")
    assert any(c["precision"] == "int4" and c["kernel"] == "fused"
               for c in full)
    for level in ("quick", "standard", "full"):
        assert all(not (c["precision"] == "pq" and c["kernel"] == "fused")
                   for c in tuning.knob_grid(level)), level
    # quick stays sub-int8-free (CPU-interpret friendly minimal set)
    assert all(c["precision"] not in ("int4", "pq")
               for c in tuning.knob_grid("quick"))


def test_gated_out_int8_candidate_can_never_win(data, cache_path,
                                                monkeypatch):
    """The acceptance clause verbatim: the bitwise end-result gate
    applies to the int8 candidate unchanged, and a gated-out int8
    candidate can never win — however fast it would have timed."""
    db, q = data
    real_search = autotune_mod._search_once

    def corrupt_int8(queries, dbx, k, margin, knobs):
        d, i = real_search(queries, dbx, k, margin, knobs)
        if knobs["precision"] == "int8":
            i = np.array(i)
            i[0, 0] = (i[0, 0] + 1) % dbx.shape[0]  # one wrong neighbor
        return d, i

    monkeypatch.setattr(autotune_mod, "_search_once", corrupt_int8)
    tuning.reset_counters()
    grid = [dict(tuning.DEFAULT_KNOBS),
            {**tuning.DEFAULT_KNOBS, "precision": "int8"}]
    entry = tuning.autotune(db, q, 5, margin=8, grid=grid, runs=1,
                            cache_path=cache_path)
    assert entry["timings_ms"]["precision=int8"] is None  # never timed
    assert "bitwise gate" in entry["errors"]["precision=int8"]
    assert entry["knobs"]["precision"] != "int8"
    assert tuning.counters()["candidates_gated_out"] >= 1


def test_int8_candidate_eligible_when_results_match(rng, cache_path):
    """On int8-exactly-representable data the int8 candidate passes the
    bitwise gate (final results == reference) and is timed — eligibility
    is decided by the gate, not by precision prejudice."""
    db = rng.integers(-100, 101, size=(700, 16)).astype(np.float32)
    db[:, 0] = 127.0  # pins every row scale at exactly 1.0
    q = rng.integers(-100, 101, size=(9, 16)).astype(np.float32)
    q[:, 0] = 127.0
    grid = [dict(tuning.DEFAULT_KNOBS),
            {**tuning.DEFAULT_KNOBS, "precision": "int8"}]
    entry = tuning.autotune(db, q, 5, margin=8, grid=grid, runs=1,
                            cache_path=cache_path)
    assert entry["timings_ms"]["precision=int8"] is not None
    assert "precision=int8" not in entry["errors"]


# -- the "throughput" grid profile (bulk kNN-join satellite) --------------
def test_throughput_profile_grid_is_a_strict_superset():
    """The throughput profile EXTENDS each level with the large-block_q
    ladder; the latency grids (and therefore every existing winner)
    are byte-identical to the pre-profile ones."""
    for level in ("quick", "standard", "full"):
        lat = tuning.knob_grid(level)
        thr = tuning.knob_grid(level, profile="throughput")
        assert lat == tuning.knob_grid(level, profile="latency")
        assert len(thr) > len(lat)
        for cand in lat:
            assert cand in thr
        # the extension IS the large-superblock ladder
        assert any((c.get("block_q") or 0) >= 512 for c in thr), level
        assert all((c.get("block_q") or 0) < 512 for c in lat), level
    with pytest.raises(ValueError, match="profile"):
        tuning.knob_grid("standard", profile="bulk")


def test_throughput_grid_fits_the_vmem_budget_everywhere():
    """No fits-nowhere arms: every throughput candidate places on at
    least one known device kind under the VMEM budget model at the
    headline shape — the same pricing check_vmem sweeps in CI."""
    from knn_tpu.analysis import vmem

    for knobs in tuning.knob_grid("full", profile="throughput"):
        full = {**tuning.DEFAULT_KNOBS, **knobs}
        assert vmem.fits_some_kind(full, **vmem.HEADLINE_SHAPE), knobs


def test_profile_cache_keys_are_disjoint_and_latency_is_unchanged():
    from knn_tpu.tuning.cache import cache_key

    assert tuning.PROFILES == ("latency", "throughput")
    base = cache_key("TPU v5e", 1_000_000, 128, 100, "l2", "bf16x3")
    lat = cache_key("TPU v5e", 1_000_000, 128, 100, "l2", "bf16x3",
                    profile="latency")
    thr = cache_key("TPU v5e", 1_000_000, 128, 100, "l2", "bf16x3",
                    profile="throughput")
    assert lat == base  # old persisted winners keep hitting
    assert thr == base + "|throughput"  # disjoint rows, never clobber
    with pytest.raises(ValueError, match="profile"):
        cache_key("TPU v5e", 1, 1, 1, "l2", None, profile="join")


def test_autotune_throughput_profile_keys_its_own_row(data, cache_path):
    db, q = data
    grid = [dict(tuning.DEFAULT_KNOBS)]
    entry = tuning.autotune(db, q, 5, margin=8, grid=grid, runs=1,
                            cache_path=cache_path, profile="throughput")
    assert entry["profile"] == "throughput"
    raw = json.load(open(cache_path))
    (key,) = raw["entries"]
    assert key == tuning.cache_key("cpu", 700, 16, 5, "l2", None,
                                   profile="throughput")
    assert key.endswith("|throughput")
    # a latency resolve for the same shape never sees the join winner
    _, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "default"
    _, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path,
                                  profile="throughput")
    assert info["source"] == "cache"
    assert info["profile"] == "throughput"
