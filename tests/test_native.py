"""Parity tests: native C++ backend vs the JAX path on identical inputs —
the two-backend cross-check SURVEY.md §4 prescribes (the reference itself
has zero automated tests; its only oracle is the MNIST accuracy table)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from knn_tpu.data.datasets import make_blobs, save_labeled_csv, save_unlabeled_csv
from knn_tpu.models.classifier import knn_predict as jax_knn_predict
from knn_tpu.ops.normalize import minmax_apply as jax_minmax_apply
from knn_tpu.ops.normalize import minmax_stats as jax_minmax_stats
from knn_tpu.ops.topk import knn_search as jax_knn_search
from knn_tpu.pipeline import run_job
from knn_tpu.utils.config import JobConfig

native = pytest.importorskip("knn_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built (no C++ toolchain?)"
)


@pytest.fixture
def blob_data(rng):
    feats, labels = make_blobs(200, 10, 4, cluster_std=1.0, seed=11)
    # duplicate a block to force exact distance ties through both backends
    feats[150:170] = feats[100:120]
    queries = feats[180:].copy()
    return feats[:180], labels[:180], queries


def test_search_parity(blob_data):
    train, _, queries = blob_data
    nd, ni = native.knn_search(train, queries, 7)
    jd, ji = jax_knn_search(jnp.asarray(queries), jnp.asarray(train), 7)
    np.testing.assert_array_equal(ni, np.asarray(ji))
    np.testing.assert_allclose(nd, np.asarray(jd), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("metric", ["l2", "l1", "cosine", "dot"])
def test_search_parity_metrics(blob_data, metric):
    train, _, queries = blob_data
    nd, ni = native.knn_search(train, queries, 5, metric)
    jd, ji = jax_knn_search(jnp.asarray(queries), jnp.asarray(train), 5, metric)
    np.testing.assert_array_equal(ni, np.asarray(ji))


def test_predict_parity(blob_data):
    train, labels, queries = blob_data
    np_pred = native.knn_predict(train, labels, queries, k=9, num_classes=4)
    j_pred = jax_knn_predict(
        jnp.asarray(train), jnp.asarray(labels), jnp.asarray(queries), k=9, num_classes=4
    )
    np.testing.assert_array_equal(np_pred, np.asarray(j_pred))


def test_predict_vote_tie_semantics():
    # 1-D layout engineering three-way ties: first-to-reach-max must win in
    # (distance, index) neighbor order in both backends
    train = np.asarray([[0.0], [1.0], [-1.0], [2.0], [-2.0], [3.0]], dtype=np.float32)
    labels = np.asarray([2, 1, 1, 0, 0, 2], dtype=np.int32)
    queries = np.asarray([[0.0], [0.4], [-0.4]], dtype=np.float32)
    np_pred = native.knn_predict(train, labels, queries, k=5, num_classes=3)
    j_pred = jax_knn_predict(
        jnp.asarray(train), jnp.asarray(labels), jnp.asarray(queries), k=5, num_classes=3
    )
    np.testing.assert_array_equal(np_pred, np.asarray(j_pred))


def test_predict_rejects_out_of_range_labels(blob_data):
    train, labels, queries = blob_data
    bad = labels.copy()
    bad[0] = 99  # the reference would OOB-write its vote array (knn_mpi.cpp:330)
    with pytest.raises(ValueError, match="label outside"):
        native.knn_predict(train, bad, queries, k=9, num_classes=4)


def test_minmax_parity(blob_data):
    train, _, queries = blob_data
    nlo, nhi = native.minmax_stats([train, queries])
    jlo, jhi = jax_minmax_stats([jnp.asarray(train), jnp.asarray(queries)])
    np.testing.assert_allclose(nlo, np.asarray(jlo), rtol=1e-6)
    np.testing.assert_allclose(nhi, np.asarray(jhi), rtol=1e-6)
    napp = native.minmax_apply(train, nlo, nhi)
    japp = jax_minmax_apply(jnp.asarray(train), jlo, jhi)
    np.testing.assert_allclose(napp, np.asarray(japp), rtol=1e-5, atol=1e-6)


def test_minmax_constant_dim_passthrough():
    x = np.asarray([[1.0, 5.0], [2.0, 5.0]], dtype=np.float32)
    lo, hi = native.minmax_stats([x])
    out = native.minmax_apply(x, lo, hi)
    np.testing.assert_allclose(out[:, 0], [0.0, 1.0])
    np.testing.assert_allclose(out[:, 1], [5.0, 5.0])  # knn_mpi.cpp:284 guard


def test_native_csv_matches_python(tmp_path, rng):
    feats = rng.normal(size=(30, 5)).astype(np.float32)
    labels = rng.integers(0, 3, size=30).astype(np.int32)
    p = str(tmp_path / "t.csv")
    save_labeled_csv(p, feats, labels)
    arr = native.read_csv(p)
    assert arr.shape == (30, 6)
    np.testing.assert_allclose(arr[:, 0], labels)
    np.testing.assert_allclose(arr[:, 1:], feats, rtol=1e-6)


def test_native_csv_rejects_trailing_comma(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("3,4,\n1,2,\n")
    with pytest.raises(ValueError, match="parse error"):
        native.read_csv(str(p))


def test_native_accuracy():
    a = np.asarray([1, 2, 3, 4], dtype=np.int32)
    b = np.asarray([1, 0, 3, 0], dtype=np.int32)
    assert native.accuracy(a, b) == 0.5


def test_multithreaded_matches_single_thread(blob_data):
    train, labels, queries = blob_data
    one = native.knn_predict(train, labels, queries, k=7, num_classes=4, num_threads=1)
    many = native.knn_predict(train, labels, queries, k=7, num_classes=4, num_threads=4)
    np.testing.assert_array_equal(one, many)


def test_pipeline_backend_parity(tmp_path):
    feats, labels = make_blobs(240, 6, 3, cluster_std=0.8, seed=5)
    paths = {
        "train": str(tmp_path / "train.csv"),
        "val": str(tmp_path / "val.csv"),
        "test": str(tmp_path / "test.csv"),
    }
    save_labeled_csv(paths["train"], feats[:160], labels[:160])
    save_labeled_csv(paths["val"], feats[160:200], labels[160:200])
    save_unlabeled_csv(paths["test"], feats[200:])

    def cfg(backend, out):
        return JobConfig(
            train_file=paths["train"], test_file=paths["test"], val_file=paths["val"],
            output_file=str(tmp_path / out), k=5, backend=backend,
            query_shards=4, db_shards=2 if backend == "jax" else 1,
        )

    jax_res = run_job(cfg("jax", "out_jax.csv"))
    nat_res = run_job(cfg("native", "out_native.csv"))
    np.testing.assert_array_equal(jax_res.test_labels, nat_res.test_labels)
    np.testing.assert_array_equal(jax_res.val_labels, nat_res.val_labels)
    assert jax_res.val_accuracy == nat_res.val_accuracy
