"""Radius-neighbors search + classifier vs float64 NumPy oracles.

Radii are chosen at the midpoint of the widest inter-distance gap near a
target quantile of the fixture's true distance distribution — nonempty
neighbor sets AND boundary-safe by construction (float32-vs-float64
arithmetic cannot flip membership, the documented ops.radius contract).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from knn_tpu.models.radius import RadiusNeighborsClassifier
from knn_tpu.ops.radius import (
    SENTINEL_IDX,
    count_within,
    radius_search,
    radius_threshold,
)
from knn_tpu.parallel import ShardedKNN, make_mesh


def _oracle_d(db, q, metric):
    db64, q64 = db.astype(np.float64), q.astype(np.float64)
    if metric == "l2":
        return np.sqrt(((db64[None] - q64[:, None]) ** 2).sum(-1))
    if metric == "l1":
        return np.abs(db64[None] - q64[:, None]).sum(-1)
    dn = db64 / np.linalg.norm(db64, axis=-1, keepdims=True)
    qn = q64 / np.linalg.norm(q64, axis=-1, keepdims=True)
    return 1.0 - qn @ dn.T  # cosine


def _safe_radius(d, quantile):
    """A radius at the midpoint of the widest gap between consecutive
    distance values near the target quantile — every point sits at least
    half that gap from the boundary."""
    flat = np.sort(d.ravel())
    target = np.quantile(flat, quantile)
    lo = np.searchsorted(flat, target * 0.9)
    hi = np.searchsorted(flat, target * 1.1)
    seg = flat[max(lo, 1) - 1 : min(hi + 1, flat.size)]
    gaps = np.diff(seg)
    j = int(np.argmax(gaps))
    radius = float((seg[j] + seg[j + 1]) / 2)
    assert gaps[j] > 4e-4 * max(radius, 1.0), "no safe gap in fixture"
    return radius


def _sets(d, radius):
    return [set(np.flatnonzero(row <= radius).tolist()) for row in d]


@pytest.fixture
def data(rng):
    db = (rng.random((400, 12)) * 10).astype(np.float32)
    q = (rng.random((25, 12)) * 10).astype(np.float32)
    return db, q


@pytest.mark.parametrize("metric", ["l2", "l1", "cosine"])
def test_radius_search_matches_oracle(data, metric):
    db, q = data
    d64 = _oracle_d(db, q, metric)
    radius = _safe_radius(d64, 0.02)
    sets = _sets(d64, radius)
    assert sum(len(s) for s in sets) > 25  # fixture is non-vacuous
    M = max(len(s) for s in sets) + 3
    d, i, counts = radius_search(q, db, radius, max_neighbors=M,
                                 metric=metric, train_tile=128)
    d, i, counts = np.asarray(d), np.asarray(i), np.asarray(counts)
    for qi, want in enumerate(sets):
        got = set(i[qi][i[qi] != SENTINEL_IDX].tolist())
        assert got == want, (metric, qi)
        assert counts[qi] == len(want)
        # in-radius entries form an ascending-distance prefix
        row = d[qi]
        finite = row[np.isfinite(row)]
        assert (np.diff(finite) >= 0).all()
        assert np.isinf(row[len(finite):]).all()


def test_radius_truncation_is_reported(data):
    db, q = data
    d64 = _oracle_d(db, q, "l2")
    radius = _safe_radius(d64, 0.10)  # dense sets
    sets = _sets(d64, radius)
    sizes = sorted(len(s) for s in sets)
    M = max(2, sizes[len(sizes) // 2])  # truncates the densest ~half
    assert sizes[-1] > M  # the fixture genuinely truncates somewhere
    d, i, counts = radius_search(q, db, radius, max_neighbors=M, metric="l2")
    counts = np.asarray(counts)
    # counts stay EXACT even when the result is truncated
    assert [int(c) for c in counts] == [len(s) for s in sets]
    assert (counts > M).any()
    # truncated rows are full: all M slots in-radius
    for qi in np.flatnonzero(counts > M):
        assert (np.asarray(i[qi]) != SENTINEL_IDX).all()


def test_count_within_per_query_thresholds(data, rng):
    db, q = data
    d64sq = _oracle_d(db, q, "l2") ** 2
    # per-query thresholds: each query gets its own radius, each chosen
    # boundary-safely from ITS OWN distance row
    thr = np.asarray(
        [radius_threshold(_safe_radius(row[None], 0.05), "l2")
         for row in np.sqrt(d64sq)], np.float32)
    counts = np.asarray(count_within(jnp.asarray(db), jnp.asarray(q), thr,
                                     "l2", tile=96))
    want = (d64sq <= thr[:, None].astype(np.float64)).sum(-1)
    np.testing.assert_array_equal(counts, want)


def test_radius_rejects_dot_metric(data):
    db, q = data
    with pytest.raises(ValueError, match="radius semantics"):
        radius_search(q, db, 1.0, max_neighbors=8, metric="dot")
    with pytest.raises(ValueError, match="radius must be"):
        radius_search(q, db, -1.0, max_neighbors=8, metric="l2")


class TestClassifier:
    def _clustered(self, rng):
        centers = rng.normal(size=(3, 8)).astype(np.float32) * 12
        y = (np.arange(240) % 3).astype(np.int32)
        X = centers[y] + rng.normal(size=(240, 8)).astype(np.float32)
        return X, y, centers

    def test_predict_matches_knn_within_radius(self, rng):
        X, y, centers = self._clustered(rng)
        q = centers[np.arange(30) % 3] + rng.normal(
            size=(30, 8)).astype(np.float32) * 0.5
        clf = RadiusNeighborsClassifier(
            8.0, max_neighbors=240, metric="l2").fit(X, y)
        pred = np.asarray(clf.predict(q))
        assert (pred == (np.arange(30) % 3)).all()
        assert clf.score(q, np.arange(30) % 3) == 1.0

    def test_outlier_raises_then_labels(self, rng):
        X, y, centers = self._clustered(rng)
        far = np.full((2, 8), 1e4, np.float32)
        clf = RadiusNeighborsClassifier(
            8.0, max_neighbors=240, metric="l2").fit(X, y)
        with pytest.raises(ValueError, match="no neighbors within"):
            clf.predict(far)
        clf2 = RadiusNeighborsClassifier(
            8.0, max_neighbors=240, metric="l2", outlier_label=7).fit(X, y)
        assert (np.asarray(clf2.predict(far)) == 7).all()

    def test_strict_truncation_raises_then_votes_nearest(self, rng):
        X, y, _ = self._clustered(rng)
        q = X[:4]
        clf = RadiusNeighborsClassifier(
            50.0, max_neighbors=16, metric="l2").fit(X, y)  # radius >> data
        with pytest.raises(ValueError, match="more than max_neighbors"):
            clf.predict(q)
        loose = RadiusNeighborsClassifier(
            50.0, max_neighbors=16, metric="l2", strict=False).fit(X, y)
        # nearest-16 vote == plain 16-NN vote here (all within radius)
        from knn_tpu.models.classifier import KNNClassifier

        knn = KNNClassifier(k=16, metric="l2").fit(X, y)
        np.testing.assert_array_equal(
            np.asarray(loose.predict(q)), np.asarray(knn.predict(q)))

    def test_vote_tie_break_matches_reference_semantics(self):
        # all-equidistant duplicates: label 1 reaches the tied max first
        # in (distance, index) order — the knn_mpi.cpp:324-336 rule
        X = np.zeros((6, 4), np.float32)
        y = np.array([2, 1, 1, 2, 0, 0], np.int32)
        clf = RadiusNeighborsClassifier(
            1.0, max_neighbors=6, metric="l2").fit(X, y)
        assert int(np.asarray(clf.predict(np.zeros((1, 4), np.float32)))[0]) == 1


class TestRegressor:
    def test_uniform_matches_oracle(self, rng):
        db = (rng.random((200, 10)) * 10).astype(np.float32)
        yv = rng.normal(size=200).astype(np.float32)
        q = (rng.random((15, 10)) * 10).astype(np.float32)
        d64 = _oracle_d(db, q, "l2")
        radius = _safe_radius(d64, 0.08)
        sets = _sets(d64, radius)
        assert all(sets), "fixture: every query needs >= 1 neighbor"
        from knn_tpu.models.radius import RadiusNeighborsRegressor

        reg = RadiusNeighborsRegressor(
            radius, max_neighbors=max(len(s) for s in sets) + 2).fit(db, yv)
        pred = np.asarray(reg.predict(q))
        want = np.array([yv[sorted(s)].astype(np.float64).mean()
                         for s in sets])
        np.testing.assert_allclose(pred, want, rtol=1e-5)
        assert reg.score(q, want) > 0.999999

    def test_distance_weights_and_outliers(self, rng):
        db = (rng.random((200, 10)) * 10).astype(np.float32)
        yv = rng.normal(size=200).astype(np.float32)
        q = (rng.random((10, 10)) * 10).astype(np.float32)
        d64 = _oracle_d(db, q, "l2")
        radius = _safe_radius(d64, 0.08)
        sets = _sets(d64, radius)
        from knn_tpu.models.radius import RadiusNeighborsRegressor

        reg = RadiusNeighborsRegressor(
            radius, max_neighbors=max(len(s) for s in sets) + 2,
            weights="distance").fit(db, yv)
        pred = np.asarray(reg.predict(q))
        for qi, s in enumerate(sets):
            idxs = sorted(s)
            dd = d64[qi, idxs]
            w = 1.0 / np.maximum(dd, 1e-12)
            want = (w * yv[idxs].astype(np.float64)).sum() / w.sum()
            np.testing.assert_allclose(pred[qi], want, rtol=1e-4)
        # outliers: raise by default, fill when outlier_value given
        far = np.full((2, 10), 1e4, np.float32)
        with pytest.raises(ValueError, match="no neighbors"):
            reg.predict(far)
        reg2 = RadiusNeighborsRegressor(
            radius, max_neighbors=64, outlier_value=-3.5).fit(db, yv)
        assert (np.asarray(reg2.predict(far)) == np.float32(-3.5)).all()


def test_failed_fit_leaves_no_inferred_state(rng):
    # a shape-mismatched fit must NOT poison num_classes: the next
    # (correct) fit would silently one-hot with too few bins
    X = (rng.random((20, 4)) * 10).astype(np.float32)
    clf = RadiusNeighborsClassifier(5.0, max_neighbors=8)
    with pytest.raises(ValueError, match="bad shapes"):
        clf.fit(X, np.array([0, 1, 2], np.int32))
    assert clf.num_classes is None
    clf.fit(X, (np.arange(20) % 10).astype(np.int32))
    assert clf.num_classes == 10


def test_regressor_score_sklearn_conventions(rng):
    from knn_tpu.models.radius import RadiusNeighborsRegressor

    X = (rng.random((30, 4)) * 10).astype(np.float32)
    # constant targets predicted exactly -> R^2 = 1.0 (sklearn), not 0.0
    reg = RadiusNeighborsRegressor(1e3, max_neighbors=30).fit(
        X, np.ones(30, np.float32))
    assert reg.score(X[:5], np.ones(5)) == 1.0
    # multi-output: per-output R^2 averaged uniformly — an output with
    # huge variance must not drown a poorly-predicted small one
    y2 = np.stack([np.ones(30), np.arange(30, dtype=np.float64) * 100],
                  axis=1).astype(np.float32)
    reg2 = RadiusNeighborsRegressor(1e3, max_neighbors=30).fit(X, y2)
    s = reg2.score(X[:6], np.stack(
        [np.zeros(6), np.asarray(reg2.predict(X[:6]))[:, 1]], axis=1))
    # output 0: constant truth (0) never predicted (pred=1) -> 0.0;
    # output 1: exact -> 1.0; uniform average = 0.5
    assert s == 0.5, s


def test_sharded_radius_matches_single_device(data):
    db, q = data
    d64 = _oracle_d(db, q, "l2")
    radius = _safe_radius(d64, 0.02)
    M = max(len(s) for s in _sets(d64, radius)) + 3
    ref_d, ref_i, ref_c = radius_search(q, db, radius, max_neighbors=M,
                                        metric="l2")
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5)
    d, i, c = prog.radius_search(q, radius, max_neighbors=M)
    # counts and per-row MEMBERSHIP are exact; positional order can swap
    # for near-tied rows whose f32 values differ by an ulp between the
    # two program structures (each program is internally lexicographic
    # over ITS OWN values), and values agree to f32 ulps only
    np.testing.assert_array_equal(c, np.asarray(ref_c))
    ref_i = np.asarray(ref_i)
    for qi in range(q.shape[0]):
        assert (set(i[qi][i[qi] >= 0].tolist())
                == set(ref_i[qi][ref_i[qi] >= 0].tolist())), qi
    ref_d = np.asarray(ref_d)
    np.testing.assert_array_equal(np.isinf(d), np.isinf(ref_d))
    np.testing.assert_allclose(d[np.isfinite(d)], ref_d[np.isfinite(ref_d)],
                               rtol=1e-5)


def test_sharded_radius_guards(data):
    db, q = data
    # bf16 placements are refused: the bf16-ranked mask vs f32 count
    # would widen the boundary band ~2000x
    prog16 = ShardedKNN(db, mesh=make_mesh(8, 1), k=5,
                        compute_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="float32 placement"):
        prog16.radius_search(q, 5.0, max_neighbors=8)
    # a max_neighbors wider than the db shard must RAISE, never silently
    # narrow (counts > M truncation detection would misread a clamped
    # result as complete)
    prog = ShardedKNN(db, mesh=make_mesh(1, 8), k=5)  # 50-row shards
    with pytest.raises(ValueError, match="exceeds db shard size"):
        prog.radius_search(q, 5.0, max_neighbors=128)


def test_sharded_radius_cosine(data):
    db, q = data
    d64 = _oracle_d(db, q, "cosine")
    radius = _safe_radius(d64, 0.02)
    sets = _sets(d64, radius)
    assert sum(len(s) for s in sets) > 25
    M = max(len(s) for s in sets) + 3
    prog = ShardedKNN(db, mesh=make_mesh(2, 4), k=5, metric="cosine")
    d, i, c = prog.radius_search(q, radius, max_neighbors=M)
    for qi, want in enumerate(sets):
        got = set(i[qi][i[qi] != SENTINEL_IDX].tolist())
        assert got == want, qi
        assert c[qi] == len(want)


def test_cityblock_alias_matches_l1(data):
    """ADVICE r5: 'cityblock' passes radius_threshold's eager validation
    but used to die inside the search dispatch — the alias must now run,
    and run IDENTICALLY to 'l1' (same threshold, same dispatch)."""
    db, q = data
    d_l1, i_l1, c_l1 = radius_search(q, db, 9.0, max_neighbors=16,
                                     metric="l1")
    d_cb, i_cb, c_cb = radius_search(q, db, 9.0, max_neighbors=16,
                                     metric="cityblock")
    np.testing.assert_array_equal(np.asarray(d_l1), np.asarray(d_cb))
    np.testing.assert_array_equal(np.asarray(i_l1), np.asarray(i_cb))
    np.testing.assert_array_equal(np.asarray(c_l1), np.asarray(c_cb))
    # count_within dispatches the alias too
    np.testing.assert_array_equal(
        np.asarray(count_within(db, q, 9.0, "cityblock")),
        np.asarray(count_within(db, q, 9.0, "l1")),
    )


def test_sharded_radius_l1_falls_back_to_single_device(data):
    """The docstring's promised L1 fallback exists: a host-array-built
    ShardedKNN routes L1 radius queries through the single-device
    ops.radius path (one pairwise computation for mask AND count), with
    results identical to calling it directly."""
    db, q = data
    d64 = _oracle_d(db, q, "l1")
    radius = _safe_radius(d64, 0.02)
    M = max(len(s) for s in _sets(d64, radius)) + 3
    ref = radius_search(q, db, radius, max_neighbors=M, metric="l1")
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5, metric="l1")
    got = prog.radius_search(q, radius, max_neighbors=M)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError):
        prog.radius_search(q, radius, max_neighbors=0)
