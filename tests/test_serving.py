"""Shape-bucketed serving engine (knn_tpu.serving): exactness across
bucket boundaries, the compile-count bound, warmup, micro-batching, and
trace replay — on the 8-virtual-device CPU mesh.

Exactness contract (serving.engine module docstring): padding is
arithmetic-transparent, so bucketed results are BITWISE identical to a
direct ``search()`` of the same placed batch; against the *unpadded*
direct call, neighbor identity and lexicographic tie-break order are
preserved on every backend, while distances additionally match bitwise
only where the backend's matmul reduction order is batch-shape invariant
(TPU MXU — CPU XLA's own direct calls already differ across batch
shapes in the last float bits, independent of this engine).
"""

import numpy as np
import pytest

from knn_tpu.parallel import ShardedKNN, make_mesh
from knn_tpu.serving import (
    QueryQueue,
    ServingEngine,
    bucket_for,
    bucket_ladder,
    parse_buckets,
    split_sizes,
)
from knn_tpu.serving.buckets import normalize_ladder

K = 7
DIM = 12
BUCKETS = (8, 16, 32)


# -- ladder unit tests (pure python) --------------------------------------
def test_bucket_ladder_geometric():
    assert bucket_ladder(8, 64) == (8, 16, 32, 64)
    # non-power-of-two top rung is kept exactly
    assert bucket_ladder(8, 100) == (8, 16, 32, 64, 100)
    assert bucket_ladder(5, 5) == (5,)
    with pytest.raises(ValueError):
        bucket_ladder(0, 8)
    with pytest.raises(ValueError):
        bucket_ladder(16, 8)
    with pytest.raises(ValueError):
        bucket_ladder(8, 64, growth=1.0)


def test_bucket_for_boundaries():
    assert bucket_for(BUCKETS, 1) == 8
    assert bucket_for(BUCKETS, 8) == 8
    assert bucket_for(BUCKETS, 9) == 16
    assert bucket_for(BUCKETS, 32) == 32
    assert bucket_for(BUCKETS, 33) is None  # oversize: caller splits
    with pytest.raises(ValueError):
        bucket_for(BUCKETS, 0)


def test_parse_buckets():
    assert parse_buckets(None) is None
    assert parse_buckets("") is None
    assert parse_buckets("auto") == bucket_ladder()
    assert parse_buckets("64, 8,16") == (8, 16, 64)
    assert parse_buckets([32, 8, 8]) == (8, 32)
    with pytest.raises(ValueError):
        parse_buckets("8,x")
    with pytest.raises(ValueError):
        normalize_ladder([])


def test_split_sizes():
    assert split_sizes(70, 32) == [32, 32, 6]
    assert split_sizes(32, 32) == [32]
    assert split_sizes(3, 32) == [3]
    with pytest.raises(ValueError):
        split_sizes(0, 32)


# -- engine fixtures -------------------------------------------------------
@pytest.fixture(scope="module")
def served():
    rng = np.random.default_rng(3)
    db = (rng.random((400, DIM)) * 10).astype(np.float32)
    q = (rng.random((40, DIM)) * 10).astype(np.float32)
    labels = rng.integers(0, 3, 400).astype(np.int32)
    mesh = make_mesh(4, 2)
    prog = ShardedKNN(db, mesh=mesh, k=K, labels=labels, num_classes=3)
    engine = ServingEngine(prog, buckets=BUCKETS)
    return prog, engine, q


def _padded_direct(prog, q, bucket):
    """The reference result: a DIRECT search() of the bucket-padded batch."""
    qp = np.zeros((bucket, q.shape[1]), np.float32)
    qp[: q.shape[0]] = q
    d, i = prog.search(qp)
    return np.asarray(d)[: q.shape[0]], np.asarray(i)[: q.shape[0]]


# -- exactness across bucket boundaries -----------------------------------
@pytest.mark.parametrize("n", [1, 7, 8, 9, 15, 16, 17, 31, 32])
def test_bucketed_bitwise_matches_direct_across_boundaries(served, n):
    prog, engine, q = served
    d_b, i_b = engine.search(q[:n])
    # bitwise vs the direct call at the same placed batch: pad rows
    # change NOTHING about real rows, the scatter drops nothing
    d_ref, i_ref = _padded_direct(prog, q[:n], bucket_for(BUCKETS, n))
    assert np.array_equal(d_b, d_ref)
    assert np.array_equal(i_b, i_ref)
    # vs the unpadded direct call: identical neighbors in identical
    # order; distances to every matched neighbor agree to f32 roundoff
    # (bitwise on reduction-order-invariant backends — see module doc)
    d_u, i_u = prog.search(q[:n])
    assert np.array_equal(np.asarray(i_u), i_b)
    np.testing.assert_allclose(np.asarray(d_u), d_b, rtol=1e-5, atol=0)


def test_bucketed_tie_break_order_matches_direct(served):
    """Exact duplicate db rows force lexicographic (distance, index)
    ties into the top-k; the bucketed path must resolve them in the
    identical order as the direct call."""
    rng = np.random.default_rng(11)
    base = (rng.random((60, DIM)) * 10).astype(np.float32)
    db = np.concatenate([base, base, base])  # every row triplicated
    mesh = make_mesh(4, 2)
    prog = ShardedKNN(db, mesh=mesh, k=6)
    engine = ServingEngine(prog, buckets=BUCKETS)
    q = base[:20] + np.float32(1e-3)
    for n in (1, 8, 9, 20):
        _, i_b = engine.search(q[:n])
        _, i_u = prog.search(q[:n])
        assert np.array_equal(np.asarray(i_u), i_b), n


def test_oversize_request_splits(served):
    prog, engine, q = served
    assert q.shape[0] > BUCKETS[-1]
    d_b, i_b = engine.search(q)  # 40 rows > top bucket 32
    _, i_u = prog.search(q)
    assert i_b.shape == (q.shape[0], K)
    assert np.array_equal(np.asarray(i_u), i_b)
    disp = engine.stats()["per_bucket_dispatches"]
    assert disp.get(32, 0) >= 1 and disp.get(8, 0) >= 1  # 40 = 32 + 8


# -- compile-count bound ---------------------------------------------------
def test_compile_count_bounded_by_ladder(served):
    """A replayed trace of 20 DISTINCT batch sizes compiles at most
    len(buckets) programs — the serving subsystem's core promise."""
    prog, _, q = served
    engine = ServingEngine(prog, buckets=BUCKETS)
    reqs = [q[:n] for n in range(1, 21)]  # 20 distinct sizes
    results, report = engine.replay(reqs, depth=2)
    assert report["compile_count"] <= len(BUCKETS)
    assert report["executables"] <= len(BUCKETS)
    assert report["requests"] == 20
    assert report["sustained_qps"] > 0
    for n, (_, idx) in zip(range(1, 21), results):
        _, i_u = prog.search(q[:n])
        assert np.array_equal(np.asarray(i_u), idx), n


def test_warmup_precompiles_every_bucket(served):
    prog, _, q = served
    engine = ServingEngine(prog, buckets=BUCKETS)
    counts = engine.warmup()
    assert counts["search"] == len(BUCKETS)
    before = engine.stats()["compile_count"]
    engine.replay([q[:n] for n in (1, 5, 9, 17, 30)], depth=2)
    # warmed ladder: the trace triggers ZERO further compiles
    assert engine.stats()["compile_count"] == before


def test_engine_predict_matches_direct(served):
    prog, engine, q = served
    engine.warmup(ops=("predict",))
    for n in (1, 9, 40):
        assert np.array_equal(
            np.asarray(prog.predict(q[:n])), engine.predict(q[:n])
        ), n


def test_engine_validates(served):
    prog, engine, q = served
    with pytest.raises(ValueError):
        engine.submit(q[:3], op="nope")
    with pytest.raises(ValueError):
        engine.submit(q[:, :4])  # wrong dim
    with pytest.raises(ValueError):
        engine.replay([q[:2]], depth=0)
    with pytest.raises(RuntimeError):
        # no labels on this placement -> predict program must refuse
        ServingEngine(
            ShardedKNN(np.zeros((64, DIM), np.float32) + 1.0,
                       mesh=prog.mesh, k=3),
            buckets=(8,),
        ).warmup(ops=("predict",))


# -- ShardedKNN entry points ----------------------------------------------
def test_search_bucketed_and_compile_cache_stats(served):
    prog, _, q = served
    d1, i1 = prog.search_bucketed(q[:9], buckets=BUCKETS)
    d2, i2 = prog.search_bucketed(q[:9], buckets=BUCKETS)  # engine reused
    assert np.array_equal(d1, d2) and np.array_equal(i1, i2)
    _, i_u = prog.search(q[:9])
    assert np.array_equal(np.asarray(i_u), i1)
    stats = prog.compile_cache_stats()
    assert {"program_cache", "distinct_shapes", "dispatches",
            "shape_counts"} <= set(stats)
    assert stats["dispatches"] >= 1
    assert stats["serving_engines"]  # the bucketed engine is visible


# -- micro-batching queue --------------------------------------------------
def test_queue_coalesces_and_scatters_exactly(served):
    prog, engine, q = served
    with QueryQueue(engine, max_wait_ms=250.0) as qq:
        futs = [qq.submit(q[3 * j : 3 * j + 3]) for j in range(6)]
        results = [f.result(timeout=60) for f in futs]
        stats = qq.stats()
    # all six requests land inside one max-wait window -> ONE dispatch
    assert stats["requests"] == 6
    assert stats["dispatches"] == 1
    assert stats["coalesced_rows"] == 18
    # arrival-to-result latency (includes the queue wait, unlike the
    # engine's dispatch-to-result percentiles)
    assert stats["latency_ms"]["count"] == 6
    assert stats["latency_ms"]["p50"] > 0
    for j, (d, i) in enumerate(results):
        _, i_u = prog.search(q[3 * j : 3 * j + 3])
        assert np.array_equal(np.asarray(i_u), i), j
        assert d.shape == (3, K)


def test_queue_zero_wait_still_exact(served):
    prog, engine, q = served
    with QueryQueue(engine, max_wait_ms=0.0) as qq:
        futs = [qq.submit(q[n : n + 2]) for n in range(0, 12, 2)]
        for n, f in zip(range(0, 12, 2), futs):
            _, i = f.result(timeout=60)
            _, i_u = prog.search(q[n : n + 2])
            assert np.array_equal(np.asarray(i_u), i)
        assert qq.stats()["dispatches"] >= 1


def test_queue_close_flushes_pending(served):
    _, engine, q = served
    qq = QueryQueue(engine, max_wait_ms=10_000.0)  # deadline never fires
    fut = qq.submit(q[:4])
    qq.close()  # close must flush, not drop
    d, i = fut.result(timeout=5)
    assert i.shape == (4, K)
    with pytest.raises(RuntimeError):
        qq.submit(q[:2])


def test_queue_predict_op(served):
    prog, engine, q = served
    with QueryQueue(engine, max_wait_ms=100.0, op="predict") as qq:
        futs = [qq.submit(q[5 * j : 5 * j + 5]) for j in range(3)]
        for j, f in enumerate(futs):
            labels = f.result(timeout=60)
            assert np.array_equal(
                np.asarray(prog.predict(q[5 * j : 5 * j + 5])), labels
            ), j


def test_queue_validates(served):
    _, engine, _ = served
    with pytest.raises(ValueError):
        QueryQueue(engine, max_wait_ms=-1.0)
    with pytest.raises(ValueError):
        QueryQueue(engine, op="nope")


def test_queue_rejects_bad_dim_and_survives(served):
    """A malformed request is rejected at submit (wrong feature dim must
    never reach the coalescing concatenate) and the queue keeps serving
    well-formed requests afterwards."""
    prog, engine, q = served
    with QueryQueue(engine, max_wait_ms=20.0) as qq:
        with pytest.raises(ValueError):
            qq.submit(q[:3, :4])
        f = qq.submit(q[:3])
        _, i = f.result(timeout=60)
        _, i0 = prog.search(q[:3])
        assert np.array_equal(np.asarray(i0), i)


# -- trace replay (the bench's serving mode, full size) --------------------
@pytest.mark.slow
def test_trace_replay_sustained_and_bounded(served):
    """The bench.py serving sweep's shape: a log-uniform variable-batch
    trace replayed with dispatch-ahead — sustained q/s, tail latency,
    and the compile bound all present and consistent."""
    prog, _, _ = served
    rng = np.random.default_rng(5)
    pool = (rng.random((256, DIM)) * 10).astype(np.float32)
    ladder = bucket_ladder(8, 64)
    engine = ServingEngine(prog, buckets=ladder)
    engine.warmup()
    sizes = np.exp(rng.uniform(0, np.log(64), size=60)).astype(int).clip(1, 64)
    reqs = [pool[int(rng.integers(0, 256 - s)) :][: int(s)] for s in sizes]
    results, report = engine.replay(reqs, depth=2)
    assert report["compile_count"] <= len(ladder)
    assert report["total_queries"] == int(sizes.sum())
    assert report["sustained_qps"] > 0
    lat = report["latency_ms"]
    assert lat["count"] == 60
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    for s, (_, idx) in zip(sizes, results):
        assert idx.shape == (int(s), K)


def test_warmup_prebuilds_int8_placement_when_winner_says_so(
        served, tmp_path, monkeypatch):
    # a persisted autotuner winner with precision="int8" for this
    # placement's shape makes warmup() pre-quantize + place the db, so
    # the first live certified query never pays the one-time build
    from knn_tpu import tuning

    prog, _, q = served
    cache = str(tmp_path / "warm_tune.json")
    monkeypatch.setenv(tuning.CACHE_ENV, cache)
    key = tuning.cache_key(
        "cpu", prog.n_train, prog._tp.shape[1], prog.k, prog.metric, None)
    tuning.TuneCache(cache).put(
        key, {"knobs": {**tuning.DEFAULT_KNOBS, "precision": "int8"}})
    engine = ServingEngine(prog, buckets=BUCKETS)
    assert prog._int8_cache is None
    counts = engine.warmup()
    assert counts.get("int8_placement") == 1
    assert prog._int8_cache is not None


# -- metric matrix through the serving surface (join-PR satellite) --------
@pytest.mark.parametrize("metric", ["l1", "cosine", "dot"])
def test_metric_matrix_bucketed_matches_direct_search(rng, metric):
    """l1 / cosine / dot serve through search_bucketed with the same
    neighbors and tie-break order as the direct search — the bucketed
    exactness contract is metric-independent."""
    db = (rng.random((300, DIM)) * 10).astype(np.float32)
    q = (rng.random((11, DIM)) * 10).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5, metric=metric)
    ref_d, ref_i = prog.search(q)
    d, i = prog.search_bucketed(q, buckets=BUCKETS)
    np.testing.assert_array_equal(i, np.asarray(ref_i))
    np.testing.assert_allclose(d, np.asarray(ref_d), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("metric", ["l1", "cosine", "dot"])
def test_metric_matrix_serving_engine(rng, metric):
    db = (rng.random((300, DIM)) * 10).astype(np.float32)
    q = (rng.random((9, DIM)) * 10).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(4, 2), k=5, metric=metric)
    eng = ServingEngine(prog, buckets=BUCKETS)
    ref_d, ref_i = prog.search(q)
    d, i = eng.search(q)
    np.testing.assert_array_equal(i, np.asarray(ref_i))
    np.testing.assert_allclose(d, np.asarray(ref_d), rtol=1e-5,
                               atol=1e-6)
    st = eng.stats(include_slo=False)
    assert sum(st["per_bucket_dispatches"].values()) >= 1
