"""Pallas fused bin-min kernel tests (interpret mode on CPU).

Exactness always comes from the certified pipeline; the kernel-level tests
pin the candidate mechanics (bin geometry, masking, known-layout recovery).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu.ops.pallas_knn import BIN_W, knn_search_pallas, pallas_knn_candidates


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None] - queries.astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


def test_kernel_recovers_planted_neighbors(rng):
    # plant the j-th nearest neighbor in bin j — one per bin, so the
    # bin-min pass must recover ALL of them exactly
    n_bins, dim = 6, 16
    db = rng.normal(size=(n_bins * BIN_W, dim)).astype(np.float32) * 100
    query = rng.normal(size=(1, dim)).astype(np.float32)
    planted = []
    for b in range(n_bins):
        idx = b * BIN_W + int(rng.integers(BIN_W))
        db[idx] = query[0] + (b + 1) * 1e-3  # distance grows with b
        planted.append(idx)
    cand = np.asarray(
        pallas_knn_candidates(jnp.asarray(query), jnp.asarray(db), n_bins, tile_n=BIN_W)
    )
    # candidate generation is a SET contract (refine re-orders exactly);
    # bf16 scores may scramble near-tie ordering
    np.testing.assert_array_equal(np.sort(cand[0]), planted)


def test_kernel_masks_padding_rows(rng):
    # db not a multiple of tile_n: zero-padded rows are near an
    # origin-query and MUST NOT surface as candidates
    db = (rng.normal(size=(3 * BIN_W + 17, 8)).astype(np.float32) + 5.0) * 10
    query = np.zeros((1, 8), dtype=np.float32)
    cand = np.asarray(
        pallas_knn_candidates(jnp.asarray(query), jnp.asarray(db), 4, tile_n=BIN_W)
    )
    assert (cand < db.shape[0]).all()


def test_kernel_candidate_recall_on_random_data(rng):
    # statistical floor: with k << bins, most true neighbors land alone in
    # their bin; certified pipeline cleans up the rest
    db = rng.normal(size=(20 * BIN_W, 32)).astype(np.float32)
    queries = rng.normal(size=(16, 32)).astype(np.float32)
    _, true_idx = _oracle(db, queries, 5)
    cand = np.asarray(
        pallas_knn_candidates(
            jnp.asarray(queries), jnp.asarray(db), 20, tile_n=2 * BIN_W,
            compute_dtype=jnp.float32,
        )
    )
    hits = sum(
        len(set(c.tolist()) & set(t.tolist())) for c, t in zip(cand, true_idx)
    )
    assert hits / true_idx.size > 0.8


def test_pallas_certified_matches_oracle(rng):
    db = rng.normal(size=(15 * BIN_W + 31, 24)).astype(np.float32) * 20
    db[200:250] = db[:50]  # ties
    queries = rng.normal(size=(23, 24)).astype(np.float32) * 20
    ref_d, ref_i = _oracle(db, queries, 9)
    d, i, stats = knn_search_pallas(queries, db, 9, tile_n=BIN_W, margin=5)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)
    assert stats["certified"] + stats["fallback_queries"] == 23


def test_kernel_rejects_bad_geometry(rng):
    db = rng.normal(size=(256, 8)).astype(np.float32)
    q = rng.normal(size=(4, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="multiple"):
        pallas_knn_candidates(jnp.asarray(q), jnp.asarray(db), 4, tile_n=100)
    with pytest.raises(ValueError, match="bin candidates"):
        pallas_knn_candidates(jnp.asarray(q), jnp.asarray(db), 1000, tile_n=BIN_W)
