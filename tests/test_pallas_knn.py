"""Pallas fused kernel tests (interpret mode on CPU).

The kernel emits top-s-per-bin candidates plus per-bin exclusion bounds;
exactness always comes from refine + the bound certificate + fallback.
These tests pin the candidate mechanics (bin geometry, survivors, padding,
dim chunking), the *soundness of the exclusion bound* — the property the
whole one-pass certified path rests on — and the end-to-end certified
result against a float64 oracle.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu.ops.pallas_knn import (
    BIN_W,
    knn_search_pallas,
    local_certified_candidates,
    pallas_knn_candidates,
)


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None] - queries.astype(np.float64)[:, None]) ** 2).sum(-1)
    idx = np.argsort(d, axis=-1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


def test_kernel_recovers_two_planted_neighbors_per_bin(rng):
    # plant TWO of the j-th nearest neighbors in bin j: the top-2-per-bin
    # reduction must recover ALL of them (the round-2 kernel kept one per
    # bin and lost the second — the dominant fallback cause at k=100)
    n_bins, dim = 4, 16
    tile_n = n_bins * BIN_W
    db = rng.normal(size=(tile_n, dim)).astype(np.float32) * 100
    query = rng.normal(size=(1, dim)).astype(np.float32)
    planted = []
    for b in range(n_bins):
        lo, hi = rng.choice(BIN_W, size=2, replace=False)
        for j, off in enumerate((lo, hi)):
            idx = b * BIN_W + int(off)
            db[idx] = query[0] + (2 * b + j + 1) * 1e-3
            planted.append(idx)
    cand = np.asarray(
        pallas_knn_candidates(
            jnp.asarray(query), jnp.asarray(db), 2 * n_bins, tile_n=tile_n
        )
    )
    np.testing.assert_array_equal(np.sort(cand[0]), np.sort(planted))


def test_kernel_masks_padding_rows(rng):
    # db not a multiple of tile_n: PAD_VAL rows score astronomically far
    # from an origin-query and must never surface as candidates
    db = (rng.normal(size=(3 * BIN_W + 17, 8)).astype(np.float32) + 5.0) * 10
    query = np.zeros((1, 8), dtype=np.float32)
    cand = np.asarray(
        pallas_knn_candidates(jnp.asarray(query), jnp.asarray(db), 8, tile_n=BIN_W)
    )
    assert (cand < db.shape[0]).all()


def test_dim_chunking_matches_unchunked_scores(rng):
    # dim=300 spans 3 chunks (pad to 384); candidate sets must match the
    # oracle's top-k exactly on well-separated data
    db = rng.normal(size=(2 * BIN_W, 300)).astype(np.float32)
    queries = rng.normal(size=(9, 300)).astype(np.float32)
    _, true_idx = _oracle(db, queries, 3)
    cand = np.asarray(
        pallas_knn_candidates(jnp.asarray(queries), jnp.asarray(db), 16,
                              tile_n=2 * BIN_W)
    )
    for c, t in zip(cand, true_idx):
        assert set(t.tolist()) <= set(c.tolist())


def test_db_major_grid_bitwise_equal_query_major(rng):
    # the grid-order change touches ONLY iteration order: every output
    # (candidates, indices, bounds) must be bitwise-identical, across
    # single- and multi-chunk dims and uneven tile counts
    from knn_tpu.ops.pallas_knn import _bin_candidates

    for dim in (24, 300):
        db = rng.normal(size=(3 * BIN_W + 40, dim)).astype(np.float32) * 10
        queries = rng.normal(size=(11, dim)).astype(np.float32) * 10
        outs = {}
        for go in ("query_major", "db_major"):
            outs[go] = _bin_candidates(
                jnp.asarray(queries), jnp.asarray(db), block_q=8,
                tile_n=2 * BIN_W, bin_w=BIN_W, survivors=2,
                precision="bf16x3", interpret=True, binning="grouped",
                grid_order=go)
        for a, b in zip(outs["query_major"], outs["db_major"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("precision", ["highest", "bf16x3", "bf16x3f",
                                       "int8"])
@pytest.mark.parametrize("binning,grid_order", [
    ("grouped", "query_major"), ("lane", "query_major"),
    ("grouped", "db_major"),
])
def test_exclusion_bound_is_sound(rng, precision, binning, grid_order):
    # THE property the one-pass certificate rests on: every db point
    # outside the candidate set must have kernel-space score >= lb
    # (within the precision mode's tolerance), and the returned d32 must
    # be the candidates' true distances to f32 accuracy
    db = rng.normal(size=(5 * BIN_W + 60, 24)).astype(np.float32) * 10
    queries = rng.normal(size=(7, 24)).astype(np.float32) * 10
    m = 13
    d32, idx, lb = local_certified_candidates(
        jnp.asarray(queries), jnp.asarray(db), m=m, block_q=8,
        tile_n=2 * BIN_W, precision=precision, interpret=True,
        binning=binning, grid_order=grid_order,
    )
    d32 = np.asarray(d32)[:7]
    idx, lb = np.asarray(idx)[:7], np.asarray(lb)[:7]
    q64, db64 = queries.astype(np.float64), db.astype(np.float64)
    s_true = (db64**2).sum(-1)[None, :] - 2.0 * (q64 @ db64.T)
    d_true = ((db64[None] - q64[:, None]) ** 2).sum(-1)
    from knn_tpu.ops.pallas_knn import kernel_tolerance

    tol = kernel_tolerance(queries, db, precision=precision)
    for qi in range(queries.shape[0]):
        outside = np.setdiff1d(np.arange(db.shape[0]), idx[qi])
        assert s_true[qi, outside].min() >= lb[qi] - tol[qi]
        np.testing.assert_allclose(
            d32[qi], d_true[qi, idx[qi]], rtol=1e-5, atol=1e-3
        )


def test_survivor_cap_pads_output(rng):
    # tile_n=BIN_W -> 1 bin -> survivors capped at MAX_SURVIVORS=8; the
    # remaining 120 slots are sentinel-padded, selection still works
    db = rng.normal(size=(BIN_W, 8)).astype(np.float32)
    queries = rng.normal(size=(3, 8)).astype(np.float32)
    _, true_idx = _oracle(db, queries, 2)
    cand = np.asarray(
        pallas_knn_candidates(jnp.asarray(queries), jnp.asarray(db), 8,
                              tile_n=BIN_W)
    )
    for c, t in zip(cand, true_idx):
        assert set(t.tolist()) <= set(c[c < db.shape[0]].tolist())


def test_pallas_certified_matches_oracle(rng):
    db = rng.normal(size=(15 * BIN_W + 31, 24)).astype(np.float32) * 20
    db[200:250] = db[:50]  # ties
    queries = rng.normal(size=(23, 24)).astype(np.float32) * 20
    ref_d, ref_i = _oracle(db, queries, 9)
    d, i, stats = knn_search_pallas(queries, db, 9, tile_n=4 * BIN_W, margin=8)
    np.testing.assert_array_equal(i, ref_i)
    # indices are exact; distances are f32-direct unless a query escalated
    # to the float64 refine (ops.pallas_knn.RANK_SLACK contract)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)
    assert stats["certified"] + stats["fallback_queries"] == 23
    assert (stats["fallback_genuine_misses"]
            + stats["fallback_false_alarms"]) == stats["fallback_queries"]


@pytest.mark.parametrize("binning", ["lane", "grouped"])
def test_pallas_certified_survives_adversarial_bins(rng, binning):
    # cram the ENTIRE true top-k into ONE kernel bin with k >
    # MAX_SURVIVORS: the kernel keeps only the bin's top 8, the bound
    # certificate must flag the loss and the fallback must still return
    # the exact answer.  A bin is a contiguous 128-lane span in "lane"
    # mode, but one LANE across a tile's column groups in "grouped" mode
    # — each layout gets its own adversarial packing
    dim, k = 12, 10
    if binning == "lane":
        tile_n = 2 * BIN_W
        db = rng.normal(size=(4 * BIN_W, dim)).astype(np.float32) * 50
        hot = [2 * BIN_W + 3 * j for j in range(k)]  # one 128-lane bin
    else:
        tile_n = 12 * BIN_W  # 12 groups of 128 lanes per tile
        db = rng.normal(size=(tile_n, dim)).astype(np.float32) * 50
        hot = [7 + BIN_W * g for g in range(k)]  # lane 7 of groups 0..9
    query = rng.normal(size=(1, dim)).astype(np.float32)
    for j, r in enumerate(hot):
        db[r] = query[0] + (j + 1) * 1e-3
    ref_d, ref_i = _oracle(db, query, k)
    d, i, stats = knn_search_pallas(query, db, k, tile_n=tile_n, margin=4,
                                    binning=binning)
    np.testing.assert_array_equal(i, ref_i)
    assert stats["fallback_queries"] >= 1
    assert stats["fallback_genuine_misses"] >= 1


def test_pad_candidates_never_get_finite_distances(rng):
    # regression (round-3 review): kernel-padding candidate indices in
    # [rows, padded) used to be clip-gathered onto the LAST REAL row and
    # emerge with its finite distance, breaking certified exactness when
    # real survivors were scarce
    db = rng.normal(size=(132, 8)).astype(np.float32) * 10
    queries = rng.normal(size=(5, 8)).astype(np.float32) * 10
    d32, idx, lb = local_certified_candidates(
        jnp.asarray(queries), jnp.asarray(db), m=20, tile_n=2 * BIN_W,
        interpret=True,
    )
    d32, idx = np.asarray(d32)[:5], np.asarray(idx)[:5]
    pad = idx >= db.shape[0]
    assert np.isinf(d32[pad]).all()
    assert (idx[pad] == 2**31 - 1).all()


def test_preplaced_zero_padded_db_masks_pad_rows(rng):
    # pre-placed arrays follow the multihost contract: caller zero-pads
    # and passes n_train; a zero pad row sits at the origin and must not
    # surface from the pallas certified path (round-3 review finding)
    import jax

    from knn_tpu.parallel import ShardedKNN, make_mesh
    from knn_tpu.parallel.mesh import pad_to_multiple

    db = (rng.normal(size=(1001, 8)).astype(np.float32) + 4.0) * 10
    queries = np.zeros((9, 8), dtype=np.float32)  # at the origin, like pads
    ref_d, ref_i = _oracle(db, queries, 5)
    mesh = make_mesh(2, 4)
    padded, n_train = pad_to_multiple(db, 8)  # zero fill
    placed = jax.device_put(
        padded,
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("db")),
    )
    prog = ShardedKNN(placed, mesh=mesh, k=5, n_train=n_train)
    prog._train_host = db  # host copy for the certified refine
    d, i, stats = prog.search_certified(queries, selector="pallas",
                                        tile_n=2 * BIN_W)
    assert (i < n_train).all()
    np.testing.assert_array_equal(i, ref_i)


def test_kernel_rejects_bad_geometry(rng):
    db = rng.normal(size=(256, 8)).astype(np.float32)
    q = rng.normal(size=(4, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="multiple"):
        pallas_knn_candidates(jnp.asarray(q), jnp.asarray(db), 4, tile_n=100)


def test_candidate_fn_composition_on_tiny_db(rng):
    # regression (round-3 review): knn_search_certified computes
    # m = min(k+margin, n); on dbs with n <= k+margin the kernel keeps
    # n-1 rows + sentinel padding and the count certificate repairs the
    # one unexaminable row — composition must stay exact
    from knn_tpu.ops.certified import knn_search_certified

    db = rng.normal(size=(20, 6)).astype(np.float32) * 10
    queries = rng.normal(size=(7, 6)).astype(np.float32) * 10
    ref_d, ref_i = _oracle(db, queries, 5)
    d, i, stats = knn_search_certified(
        queries, db, 5, candidate_fn=pallas_knn_candidates
    )
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=1e-9)


@pytest.mark.parametrize("bin_w,survivors", [(2 * BIN_W, 3), (2 * BIN_W, 2),
                                             (BIN_W, 4)])
def test_wide_bin_geometry_matches_oracle(rng, bin_w, survivors):
    # the tunable geometry (wider bins x more survivors shrinks the
    # candidate array the final select scans): certified exactness must
    # hold for every (bin_w, survivors) the bench sweeps
    db = rng.normal(size=(9 * BIN_W + 45, 16)).astype(np.float32) * 20
    queries = rng.normal(size=(11, 16)).astype(np.float32) * 20
    ref_d, ref_i = _oracle(db, queries, 7)
    # bin_w only shapes LANE-mode binning (inert in grouped mode)
    d, i, stats = knn_search_pallas(
        queries, db, 7, tile_n=4 * BIN_W, margin=8, bin_w=bin_w,
        survivors=survivors, binning="lane",
    )
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)


def test_multi_block_output_lanes_match_oracle(rng):
    # n_bins * survivors > 128 forces a multiple-of-128-lane output block:
    # the lowering rule the round-2 kernel broke, now exercised as a
    # first-class geometry — both the _geometry arithmetic AND a real
    # kernel run at out_w = 256
    from knn_tpu.ops.pallas_knn import _geometry

    assert _geometry(4 * BIN_W, BIN_W, 64, "lane") == (4, 8, 128, 128)
    assert _geometry(16 * BIN_W, BIN_W, 2, "lane") == (16, 2, 128, 128)
    assert _geometry(32 * BIN_W, BIN_W, 8, "lane") == (32, 8, 256, 128)
    assert _geometry(160 * BIN_W, BIN_W, 1, "lane") == (160, 1, 256, 256)
    # grouped: always 128 lane-bins; out_w = survivors * 128; bin_w inert
    assert _geometry(4 * BIN_W, BIN_W, None, "grouped") == (128, 2, 256, 128)
    assert _geometry(32 * BIN_W, BIN_W, 64, "grouped") == (128, 8, 1024, 128)
    assert _geometry(160 * BIN_W, 2 * BIN_W, 1, "grouped") == (128, 1, 128, 128)

    # out_w = 256 LANE-mode kernel run: 32 bins x 8 survivors per tile
    # (explicit binning: the grouped default would change the geometry
    # and stop exercising the round-2 multi-block lowering regression)
    db = rng.normal(size=(2 * 32 * BIN_W + 77, 8)).astype(np.float32) * 5
    queries = rng.normal(size=(5, 8)).astype(np.float32) * 5
    k = 5
    ref_d, ref_i = _oracle(db, queries, k)
    d, i, _ = knn_search_pallas(queries, db, k, tile_n=32 * BIN_W, margin=6,
                                survivors=8, binning="lane")
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)

    # bound_w = 256 lane-mode kernel run: 160 bins per tile
    d, i, _ = knn_search_pallas(queries, db, k, tile_n=160 * BIN_W, margin=6,
                                survivors=1, binning="lane")
    np.testing.assert_array_equal(i, ref_i)

    # grouped multi-block out_w: 8 survivors -> out_w = 1024 (8 blocks)
    d, i, _ = knn_search_pallas(queries, db, k, tile_n=32 * BIN_W, margin=6,
                                survivors=8, binning="grouped")
    np.testing.assert_array_equal(i, ref_i)


def test_final_select_approx_stays_exact(rng):
    # approx_max_k as the final candidate select: the exclusion value is
    # restored exactly (masked min over the de-selected), so the result
    # must STILL match the float64 oracle — misses surface as fallbacks,
    # never as wrong neighbors
    db = rng.normal(size=(12 * BIN_W + 9, 24)).astype(np.float32) * 20
    db[300:340] = db[:40]  # cross-bin ties
    queries = rng.normal(size=(17, 24)).astype(np.float32) * 20
    ref_d, ref_i = _oracle(db, queries, 8)
    d, i, stats = knn_search_pallas(queries, db, 8, tile_n=4 * BIN_W,
                                    margin=8, final_select="approx")
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_allclose(d, ref_d, rtol=5e-5)


def test_bit_mask_roundtrip(rng):
    import jax

    from knn_tpu.parallel.sharded import _pack_bits_u32, unpack_bits_u32

    for b in (1, 31, 32, 33, 116, 128):
        mask = rng.random((9, b)) < 0.3
        packed = jax.jit(_pack_bits_u32)(jnp.asarray(mask))
        assert packed.shape == (9, -(-b // 32))
        out = unpack_bits_u32(np.asarray(packed), b)
        np.testing.assert_array_equal(out, mask)


def test_effective_tile_halves_for_midsize_dbs():
    # the round-4 default tile (16384) must not starve mid-size dbs of
    # candidate width: the shared halving helper shrinks the tile until
    # n_tiles * out_w covers min_width (= m+2 for certified callers)
    from knn_tpu.ops.pallas_knn import _geometry, effective_tile

    # 10k rows, need 302 lanes: one 10112-tile gives 256 -> halve
    t = effective_tile(10_000, 16384, BIN_W, None, "grouped", 302)
    assert t % BIN_W == 0
    n_tiles = -(-10_000 // t)
    assert n_tiles * _geometry(t, BIN_W, None, "grouped")[2] >= 302

    # huge db: no halving needed, the request is honored
    assert effective_tile(1_000_000, 16384, BIN_W, None, "grouped", 130) \
        == 16384
    # tiny db: tile caps at the padded rows
    assert effective_tile(200, 16384, BIN_W, None, "grouped", 4) == 256
    # bottoms out at bin_w even when the width can never be met
    assert effective_tile(100, 16384, BIN_W, None, "grouped", 10**6) == BIN_W
    # an explicitly invalid request still raises, never silently repaired
    with pytest.raises(ValueError, match="multiple"):
        effective_tile(10_000, 100, BIN_W, None, "grouped", 10)
    # lane mode: halving interacts with the survivors floor monotonically
    t = effective_tile(10_000, 16384, BIN_W, None, "lane", 600)
    n_tiles = -(-10_000 // t)
    assert n_tiles * _geometry(t, BIN_W, None, "lane")[2] >= 600


def test_default_tile_wide_margin_midsize_end_to_end(rng):
    # regression: at the 16384 default tile a 10k-row db previously
    # raised "m+2 exceeds ... survivors" for wide margins; the adaptive
    # tile must keep the certified path exact end-to-end instead
    db = rng.normal(size=(10_000, 12)).astype(np.float32) * 30
    queries = rng.normal(size=(4, 12)).astype(np.float32) * 30
    ref_d, ref_i = _oracle(db, queries, 60)
    d, i, stats = knn_search_pallas(queries, db, 60, margin=240)
    np.testing.assert_array_equal(i, ref_i)
