"""The double-buffered streaming kernel (ops.pallas_knn kernel="streaming")
in interpret mode: bitwise equality against the tiled grouped kernel at
every output level — raw bin candidates, the certified candidate stage,
and the end-to-end certified search — across tile-boundary cases (n not
divisible by tile_n, true neighbors straddling a tile edge, duplicate
distances exercising the lexicographic tie-break), plus the float64
direct-difference oracle (the pairwise_sq_l2_direct semantics in fp64).
Bitwise equality is the whole contract: the streaming pipeline changes
HOW the db reaches VMEM (explicit double-buffered DMA, one launch per
batch/shard), never WHAT is computed.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu.ops.pallas_knn import (
    BIN_W,
    _bin_candidates,
    kernel_launches_per_batch,
    knn_search_pallas,
    local_certified_candidates,
)
from tests.oracles import sq_l2, topk_lowindex


def _oracle(db, queries, k):
    return topk_lowindex(sq_l2(queries, db), k)


@pytest.mark.parametrize("dim", [24, 300])
@pytest.mark.parametrize("precision,binning", [
    ("bf16x3", "grouped"), ("bf16x3f", "grouped"), ("highest", "grouped"),
    ("bf16x3", "lane"), ("default", "grouped"),
    ("int8", "grouped"), ("int8", "lane"),
])
def test_streaming_bitwise_equals_tiled_bin_candidates(rng, dim, precision,
                                                       binning):
    # raw kernel outputs (candidates, indices, per-tile bounds) across
    # uneven tile counts (n % tile_n != 0 -> PAD_VAL padding) and both
    # single- and multi-chunk dims (300 spans 3 DIM_CHUNKs)
    db = rng.normal(size=(3 * BIN_W + 41, dim)).astype(np.float32) * 10
    queries = rng.normal(size=(11, dim)).astype(np.float32) * 10
    outs = {}
    for kern in ("tiled", "streaming"):
        outs[kern] = _bin_candidates(
            jnp.asarray(queries), jnp.asarray(db), block_q=8,
            tile_n=2 * BIN_W, bin_w=BIN_W, survivors=2,
            precision=precision, interpret=True, binning=binning,
            kernel=kern)
    for a, b in zip(outs["tiled"], outs["streaming"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("precision", ["bf16x3", "int8"])
@pytest.mark.parametrize("n_rows", [
    2 * BIN_W,          # exactly one tile
    2 * BIN_W + 1,      # one row past a tile edge
    5 * BIN_W + 60,     # several tiles, ragged tail
])
def test_streaming_bitwise_equals_tiled_certified_stage(rng, n_rows,
                                                        precision):
    # the full certified candidate stage (kernel + final select + f32
    # rescore): d32, idx, AND the exclusion bound must agree bitwise
    db = rng.normal(size=(n_rows, 24)).astype(np.float32) * 10
    queries = rng.normal(size=(7, 24)).astype(np.float32) * 10
    outs = {}
    for kern in ("tiled", "streaming"):
        outs[kern] = local_certified_candidates(
            jnp.asarray(queries), jnp.asarray(db), m=13, block_q=8,
            tile_n=2 * BIN_W, interpret=True, kernel=kern,
            precision=precision)
    for a, b in zip(outs["tiled"], outs["streaming"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_k_spanning_tile_edge_matches_oracle(rng):
    # plant the true top-k STRADDLING a tile boundary (last rows of tile
    # 0, first rows of tile 1): the carried candidate list must merge
    # across the in-kernel tile loop exactly like the tiled path's XLA
    # merge
    dim, k, tile_n = 16, 8, 2 * BIN_W
    db = rng.normal(size=(4 * BIN_W, dim)).astype(np.float32) * 50
    query = rng.normal(size=(1, dim)).astype(np.float32)
    hot = [tile_n - 4 + j for j in range(4)] + [tile_n + j for j in range(4)]
    for j, r in enumerate(hot):
        db[r] = query[0] + (j + 1) * 1e-3
    ref_d, ref_i = _oracle(db, query, k)
    for kern in ("tiled", "streaming"):
        d, i, _ = knn_search_pallas(query, db, k, tile_n=tile_n, margin=6,
                                    kernel=kern)
        np.testing.assert_array_equal(i, ref_i)
        np.testing.assert_allclose(d, ref_d, rtol=5e-5)


def test_streaming_duplicate_distances_lexicographic_ties(rng):
    # duplicate rows ACROSS tiles force exact distance ties whose
    # resolution is the documented lexicographic (distance, index)
    # order; a query placed on a duplicated pair plus a near-tie pileup
    # exercises the rank-correction path under both kernels
    db = rng.normal(size=(6 * BIN_W + 31, 12)).astype(np.float32) * 20
    db[3 * BIN_W : 3 * BIN_W + 40] = db[:40]        # tile-2 copies of tile-0 rows
    db[5 * BIN_W : 5 * BIN_W + 10] = db[100] + 1e-3  # near-tie pileup
    queries = rng.normal(size=(9, 12)).astype(np.float32) * 20
    queries[0] = db[0] + 5e-4    # lands ON a cross-tile duplicate pair
    queries[1] = db[100] + 5e-4  # lands in the pileup
    ref_d, ref_i = _oracle(db, queries, 7)
    results = {}
    for kern in ("tiled", "streaming"):
        d, i, stats = knn_search_pallas(queries, db, 7, tile_n=2 * BIN_W,
                                        margin=8, kernel=kern)
        np.testing.assert_array_equal(i, ref_i)
        np.testing.assert_allclose(d, ref_d, rtol=5e-5)
        results[kern] = (d, i, stats)
    # and the two kernels agree bitwise END TO END — certification
    # stats included (the knob/provenance blocks legitimately differ:
    # they record which kernel ran)
    np.testing.assert_array_equal(results["tiled"][0], results["streaming"][0])
    np.testing.assert_array_equal(results["tiled"][1], results["streaming"][1])
    strip = lambda s: {k: v for k, v in s.items()  # noqa: E731
                       if k not in ("pallas_knobs", "tuning")}
    assert strip(results["tiled"][2]) == strip(results["streaming"][2])


def test_streaming_sharded_search_certified_bitwise(rng):
    # the sharded certified pipeline with db shards: one streaming
    # launch PER SHARD, merged across the db axis — results bitwise
    # equal to the tiled path's
    from knn_tpu.parallel import ShardedKNN, make_mesh

    db = rng.normal(size=(1500, 12)).astype(np.float32) * 20
    queries = rng.normal(size=(9, 12)).astype(np.float32) * 20
    prog = ShardedKNN(db, mesh=make_mesh(2, 4), k=5)
    out = {}
    for kern in ("tiled", "streaming"):
        d, i, stats = prog.search_certified(
            queries, selector="pallas", margin=8, tile_n=2 * BIN_W,
            kernel=kern)
        out[kern] = (d, i, stats)
        assert stats["pallas_knobs"]["kernel"] == kern
    np.testing.assert_array_equal(out["tiled"][0], out["streaming"][0])
    np.testing.assert_array_equal(out["tiled"][1], out["streaming"][1])
    ref_d, ref_i = _oracle(db, queries, 5)
    np.testing.assert_array_equal(out["streaming"][1], ref_i)


def test_streaming_rejects_db_major():
    # the streaming launch has no db grid axis to reorder — refusing the
    # knob beats silently ignoring it
    with pytest.raises(ValueError, match="db_major"):
        _bin_candidates(
            jnp.zeros((4, 8), jnp.float32), jnp.zeros((256, 8), jnp.float32),
            block_q=8, tile_n=2 * BIN_W, bin_w=BIN_W, survivors=2,
            precision="bf16x3", interpret=True, grid_order="db_major",
            kernel="streaming")


def test_kernel_launch_accounting():
    # the bench's launch-count contract: tiled = one pipelined body
    # launch per train tile, streaming = ONE per (batch, shard)
    assert kernel_launches_per_batch("tiled", 1_000_000, 16384) == 62
    assert kernel_launches_per_batch("streaming", 1_000_000, 16384) == 1
    assert kernel_launches_per_batch("tiled", 16384, 16384) == 1
    with pytest.raises(ValueError, match="kernel"):
        kernel_launches_per_batch("warp", 1000, 128)


# --- int8 coarse arm (the quantized MXU path, ops.quantize) -------------

def _int8_exact_data(rng, n_rows, dim):
    """Integer-valued data whose per-row max is pinned at 127: the int8
    quantization is then EXACT (unit scales, zero residuals) and every
    kernel score is a small integer computed exactly by BOTH the bf16x3
    reference and the int8 arm — which is what makes FINAL results
    bitwise comparable across precisions (fallback-pattern divergence
    cannot leak into the values: all distances are < 2^24 integers,
    exact in f32 and f64 alike)."""
    db = rng.integers(-100, 101, size=(n_rows, dim)).astype(np.float32)
    db[:, 0] = 127.0  # pins max|row| -> scale exactly 1.0
    return db


@pytest.mark.parametrize("kern", ["tiled", "streaming"])
@pytest.mark.parametrize("n_rows", [
    2 * BIN_W,          # exactly one tile
    2 * BIN_W + 1,      # ragged: one row past a tile edge
    5 * BIN_W + 60,     # several tiles, ragged tail
])
def test_int8_final_results_bitwise_vs_reference(rng, n_rows, kern):
    """THE acceptance gate: precision='int8' reproduces the reference
    grouped config's FINAL certified (distances, indices) bitwise, across
    both db-streaming kernels and ragged tile counts — including
    cross-tile duplicate ties (exact distance ties resolved by the
    lexicographic rule + f64 rank correction)."""
    dim, k = 12, 7
    db = _int8_exact_data(rng, n_rows, dim)
    # cross-tile duplicates + a query ON a duplicated pair: exact ties
    dup = min(40, n_rows - 2 * BIN_W) if n_rows > 2 * BIN_W else 20
    db[n_rows - dup:] = db[:dup]
    queries = _int8_exact_data(rng, 9, dim)
    queries[0] = db[0]  # exact-tie pileup on a duplicated row
    ref_d, ref_i, _ = knn_search_pallas(queries, db, k, tile_n=2 * BIN_W,
                                        margin=8)
    d, i, stats = knn_search_pallas(queries, db, k, tile_n=2 * BIN_W,
                                    margin=8, precision="int8",
                                    kernel=kern)
    np.testing.assert_array_equal(i, ref_i)
    np.testing.assert_array_equal(d, ref_d)
    # and both equal the float64 oracle (exactness, not just agreement)
    oracle_d, oracle_i = _oracle(db, queries, k)
    np.testing.assert_array_equal(i, oracle_i)
    np.testing.assert_allclose(d, oracle_d, rtol=0, atol=0)


def test_int8_noisy_data_indices_exact_with_fallback(rng):
    """Real (non-representable) f32 data: quantization error is genuine,
    the certificate widens by the bound, and whatever falls back repairs
    — final INDICES equal the oracle unconditionally."""
    db = rng.normal(size=(5 * BIN_W + 31, 16)).astype(np.float32) * 10
    # near-tie pileup: distances inside the quantization band, forcing
    # the widened certificate to flag + repair rather than trust the rank
    queries = rng.normal(size=(8, 16)).astype(np.float32) * 10
    db[100:110] = queries[1][None, :] + rng.normal(
        size=(10, 16)).astype(np.float32) * 1e-2
    ref_d, ref_i = _oracle(db, queries, 6)
    for kern in ("tiled", "streaming"):
        d, i, stats = knn_search_pallas(queries, db, 6, tile_n=2 * BIN_W,
                                        margin=8, precision="int8",
                                        kernel=kern)
        np.testing.assert_array_equal(i, ref_i)
        np.testing.assert_allclose(d, ref_d, rtol=5e-5)
        assert stats["fallback_queries"] + stats["certified"] == 8


def test_int8_sharded_search_certified_bitwise(rng):
    # sharded db: the quantized placement shards along the db axis, one
    # launch per shard, lb pmin'd — tiled and streaming int8 agree
    # bitwise end to end and match the oracle indices
    from knn_tpu.parallel import ShardedKNN, make_mesh

    db = rng.normal(size=(1500, 12)).astype(np.float32) * 20
    queries = rng.normal(size=(9, 12)).astype(np.float32) * 20
    prog = ShardedKNN(db, mesh=make_mesh(2, 4), k=5)
    out = {}
    for kern in ("tiled", "streaming"):
        d, i, stats = prog.search_certified(
            queries, selector="pallas", margin=8, tile_n=2 * BIN_W,
            precision="int8", kernel=kern)
        out[kern] = (d, i)
        assert stats["pallas_knobs"]["precision"] == "int8"
    np.testing.assert_array_equal(out["tiled"][0], out["streaming"][0])
    np.testing.assert_array_equal(out["tiled"][1], out["streaming"][1])
    ref_d, ref_i = _oracle(db, queries, 5)
    np.testing.assert_array_equal(out["streaming"][1], ref_i)
    # the quantized placement was built once and cached
    assert prog._int8_cache is not None


def test_int8_uncertifiable_default_precision_still_refused(rng):
    from knn_tpu.parallel import ShardedKNN, make_mesh

    db = rng.normal(size=(600, 8)).astype(np.float32)
    prog = ShardedKNN(db, mesh=make_mesh(1, 1), k=3)
    with pytest.raises(ValueError, match="tolerance model"):
        prog.search_certified(db[:4], selector="pallas",
                              precision="default")
