import jax.numpy as jnp
import numpy as np

import oracles
from knn_tpu.ops import vote


def test_simple_majority():
    labels = jnp.asarray([[1, 1, 2], [0, 2, 2], [3, 3, 3]])
    got = np.asarray(vote.majority_vote(labels, 4))
    np.testing.assert_array_equal(got, [1, 2, 3])


def test_tie_goes_to_first_reacher():
    # counts tie 2-2; label 5 reaches count 2 at position 2, label 1 at
    # position 3 -> 5 wins (reference running-argmax semantics)
    labels = jnp.asarray([[5, 1, 5, 1]])
    assert int(vote.majority_vote(labels, 6)[0]) == 5
    # reversed arrival order flips the winner
    labels = jnp.asarray([[1, 5, 1, 5]])
    assert int(vote.majority_vote(labels, 6)[0]) == 1


def test_matches_reference_loop_oracle(rng):
    labels = rng.integers(0, 7, size=(200, 15))
    got = np.asarray(vote.majority_vote(jnp.asarray(labels), 7))
    ref = oracles.running_argmax_vote(labels, 7)
    np.testing.assert_array_equal(got, ref)


def test_batched_shapes(rng):
    labels = rng.integers(0, 4, size=(3, 5, 9))
    got = vote.majority_vote(jnp.asarray(labels), 4)
    assert got.shape == (3, 5)
    flat = np.asarray(vote.majority_vote(jnp.asarray(labels.reshape(15, 9)), 4))
    np.testing.assert_array_equal(np.asarray(got).reshape(-1), flat)


def test_vote_counts(rng):
    labels = rng.integers(0, 5, size=(10, 20))
    counts = np.asarray(vote.vote_counts(jnp.asarray(labels), 5))
    for i in range(10):
        np.testing.assert_array_equal(counts[i], np.bincount(labels[i], minlength=5))
