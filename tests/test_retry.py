"""Bounded-retry fault injection for the sharded search paths (SURVEY §5
failure row; VERDICT r3 item 8): a transient device error inside a long
sweep must be retried per batch — on the dispatch side (the program call
raises) and on the fetch side (the async error surfaces at np.asarray) —
without killing the job or changing the exact result.  Caller bugs
(ValueError/TypeError) must NOT be retried.
"""

import numpy as np
import pytest

from knn_tpu.parallel import sharded as sh
from knn_tpu.parallel.mesh import make_mesh
from knn_tpu.parallel.sharded import ShardedKNN


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None] - queries.astype(np.float64)[:, None])
         ** 2).sum(-1)
    idx = np.lexsort(
        (np.broadcast_to(np.arange(db.shape[0]), d.shape), d), axis=-1
    )[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


@pytest.fixture
def data(rng):
    db = (rng.random((500, 12)) * 20).astype(np.float32)
    q = (rng.random((10, 12)) * 20).astype(np.float32)
    return db, q


class _FlakyArray:
    """Defers to a real array but raises ONCE at host-fetch time —
    models an async device failure surfacing at the transfer."""

    def __init__(self, arr, state):
        self._arr = arr
        self._state = state

    def __array__(self, dtype=None, copy=None):
        if not self._state["tripped"]:
            self._state["tripped"] = True
            raise RuntimeError("injected async device failure")
        a = np.asarray(self._arr)
        return a.astype(dtype) if dtype is not None else a


def test_search_retries_dispatch_failure(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    state = {"fails": 1}

    def flaky_knn_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            if state["fails"] > 0:
                state["fails"] -= 1
                raise RuntimeError("injected dispatch failure")
            return prog(*pa, **pkw)

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", flaky_knn_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    _, i = prog.search(q)
    np.testing.assert_array_equal(np.asarray(i), ref_i)
    assert state["fails"] == 0  # the injection actually fired


def test_certified_counted_retries_fetch_failure(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    state = {"tripped": False}

    def flaky_knn_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            d, i = prog(*pa, **pkw)
            if not state["tripped"]:
                return d, _FlakyArray(i, state)
            return d, i

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", flaky_knn_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    d, i, stats = prog.search_certified(q, selector="exact", margin=6)
    np.testing.assert_array_equal(i, ref_i)
    assert state["tripped"]


def test_certified_pallas_retries_fetch_failure(data, monkeypatch):
    db, q = data
    real = sh._pallas_certified_program
    state = {"tripped": False}

    def flaky_pallas_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            out = prog(*pa, **pkw)
            if not state["tripped"]:
                return _FlakyArray(out, state)
            return out

        return wrapper

    monkeypatch.setattr(sh, "_pallas_certified_program", flaky_pallas_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    d, i, stats = prog.search_certified(q, selector="pallas", margin=6)
    np.testing.assert_array_equal(i, ref_i)
    assert state["tripped"]


def test_retry_gives_up_after_bounded_attempts(data, monkeypatch):
    db, q = data
    real = sh._knn_program

    def always_broken(*a, **kw):
        real(*a, **kw)  # keep compile cost honest

        def wrapper(*pa, **pkw):
            raise RuntimeError("permanently broken")

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", always_broken)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    with pytest.raises(RuntimeError, match="failed after"):
        prog.search(q)


def test_caller_bugs_are_not_retried(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    calls = {"n": 0}

    def buggy(*a, **kw):
        real(*a, **kw)

        def wrapper(*pa, **pkw):
            calls["n"] += 1
            raise ValueError("caller bug")

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", buggy)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    with pytest.raises(ValueError, match="caller bug"):
        prog.search(q)
    assert calls["n"] == 1  # no retry on ValueError
