"""Bounded-retry fault injection for the sharded search paths (SURVEY §5
failure row; VERDICT r3 item 8): a transient device error inside a long
sweep must be retried per batch — on the dispatch side (the program call
raises) and on the fetch side (the async error surfaces at np.asarray) —
without killing the job or changing the exact result.  Caller bugs
(ValueError/TypeError) must NOT be retried.
"""

import numpy as np
import pytest

from knn_tpu.parallel import sharded as sh
from knn_tpu.parallel.mesh import make_mesh
from knn_tpu.parallel.sharded import ShardedKNN


def _oracle(db, queries, k):
    d = ((db.astype(np.float64)[None] - queries.astype(np.float64)[:, None])
         ** 2).sum(-1)
    idx = np.lexsort(
        (np.broadcast_to(np.arange(db.shape[0]), d.shape), d), axis=-1
    )[:, :k]
    return np.take_along_axis(d, idx, axis=-1), idx


@pytest.fixture
def data(rng):
    db = (rng.random((500, 12)) * 20).astype(np.float32)
    q = (rng.random((10, 12)) * 20).astype(np.float32)
    return db, q


class _FlakyArray:
    """Defers to a real array but raises ONCE at host-fetch time —
    models an async device failure surfacing at the transfer."""

    def __init__(self, arr, state):
        self._arr = arr
        self._state = state

    def __array__(self, dtype=None, copy=None):
        if not self._state["tripped"]:
            self._state["tripped"] = True
            raise RuntimeError("injected async device failure")
        a = np.asarray(self._arr)
        return a.astype(dtype) if dtype is not None else a


def test_search_retries_dispatch_failure(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    state = {"fails": 1}

    def flaky_knn_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            if state["fails"] > 0:
                state["fails"] -= 1
                raise RuntimeError("injected dispatch failure")
            return prog(*pa, **pkw)

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", flaky_knn_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    _, i = prog.search(q)
    np.testing.assert_array_equal(np.asarray(i), ref_i)
    assert state["fails"] == 0  # the injection actually fired


def test_certified_counted_retries_fetch_failure(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    state = {"tripped": False}

    def flaky_knn_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            d, i = prog(*pa, **pkw)
            if not state["tripped"]:
                return d, _FlakyArray(i, state)
            return d, i

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", flaky_knn_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    d, i, stats = prog.search_certified(q, selector="exact", margin=6)
    np.testing.assert_array_equal(i, ref_i)
    assert state["tripped"]


def test_certified_pallas_retries_fetch_failure(data, monkeypatch):
    db, q = data
    real = sh._pallas_certified_program
    state = {"tripped": False}

    def flaky_pallas_program(*a, **kw):
        prog = real(*a, **kw)

        def wrapper(*pa, **pkw):
            out = prog(*pa, **pkw)
            if not state["tripped"]:
                return _FlakyArray(out, state)
            return out

        return wrapper

    monkeypatch.setattr(sh, "_pallas_certified_program", flaky_pallas_program)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    _, ref_i = _oracle(db, q, 5)
    d, i, stats = prog.search_certified(q, selector="pallas", margin=6)
    np.testing.assert_array_equal(i, ref_i)
    assert state["tripped"]


def test_retry_gives_up_after_bounded_attempts(data, monkeypatch):
    db, q = data
    real = sh._knn_program

    def always_broken(*a, **kw):
        real(*a, **kw)  # keep compile cost honest

        def wrapper(*pa, **pkw):
            raise RuntimeError("permanently broken")

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", always_broken)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    with pytest.raises(RuntimeError, match="failed after"):
        prog.search(q)


@pytest.fixture
def no_backoff(monkeypatch):
    # pure-unit policy tests need no real exponential sleeps
    monkeypatch.setattr(sh, "_retry_wait", lambda attempt: None)


def test_deterministic_failures_are_not_retried(no_backoff):
    # ADVICE r4: a Mosaic compile error / OOM is deterministic — retrying
    # it only adds ~3.5 s of backoff per batch before the real error
    # surfaces.  The signature classifier must propagate it on attempt 1.
    calls = {"n": 0}

    def oom():
        calls["n"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        sh._retry_transient(oom, "probe")
    assert calls["n"] == 1

    calls["n"] = 0

    def mosaic():
        calls["n"] += 1
        raise RuntimeError("Mosaic failed to compile TPU kernel")

    with pytest.raises(RuntimeError, match="Mosaic"):
        sh._retry_transient(mosaic, "probe")
    assert calls["n"] == 1


def test_unknown_repeating_failure_gives_up_early(no_backoff):
    # an unrecognized error that repeats VERBATIM is deterministic in
    # effect: stop after the repeat (2 calls), not the full window (3)
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise RuntimeError("some novel permanent failure")

    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        sh._retry_transient(broken, "probe")
    assert calls["n"] == 2


def test_known_transient_gets_full_retry_window(no_backoff):
    # relay-vocabulary errors (UNAVAILABLE etc.) keep the full bounded
    # window even when attempts fail identically — that is the hiccup
    # the backoff exists to outlast
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: connection reset by relay")
        return "ok"

    assert sh._retry_transient(flaky, "probe") == "ok"
    assert calls["n"] == 3


def test_fetch_deterministic_failure_not_redispatched(no_backoff):
    state = {"redo": 0}

    class OOMArray:
        def __array__(self, dtype=None, copy=None):
            raise RuntimeError("RESOURCE_EXHAUSTED: device OOM")

    def redo():
        state["redo"] += 1
        return np.zeros(3)

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        sh._fetch_or_redispatch(OOMArray(), redo, "fetch")
    assert state["redo"] == 0


def test_caller_bugs_are_not_retried(data, monkeypatch):
    db, q = data
    real = sh._knn_program
    calls = {"n": 0}

    def buggy(*a, **kw):
        real(*a, **kw)

        def wrapper(*pa, **pkw):
            calls["n"] += 1
            raise ValueError("caller bug")

        return wrapper

    monkeypatch.setattr(sh, "_knn_program", buggy)
    prog = ShardedKNN(db, mesh=make_mesh(2, 2), k=5)
    with pytest.raises(ValueError, match="caller bug"):
        prog.search(q)
    assert calls["n"] == 1  # no retry on ValueError
