"""Admission control (knn_tpu.serving.admission + QueryQueue wiring):
bounded depth with explicit rejection, per-tenant token-bucket quotas,
deadline-aware shedding (submit-time estimate + queued expiry),
starvation-safe aged-priority ordering, per-tenant metrics/SLOs, the
brownout acceptance (at 5x the measured capacity the queue sheds with
explicit outcomes, admitted p99 stays within the SLO, no tenant is
starved, and throughput recovers after the burst), and the
disabled-mode bitwise-identity contract."""

import time

import numpy as np
import pytest

from knn_tpu import loadgen, obs
from knn_tpu.obs import names as mn
from knn_tpu.obs import slo
from knn_tpu.parallel import ShardedKNN, make_mesh
from knn_tpu.serving import (
    AdmissionConfig,
    AdmissionController,
    DeadlineError,
    QueryQueue,
    QueueFullError,
    QuotaExceededError,
    ServingEngine,
)

K = 7
DIM = 12
BUCKETS = (8, 16, 32)


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Every test starts from an empty ENABLED registry/ring/SLO/health
    state (queues register health hooks and mint counters)."""
    obs.reset(enabled=True)
    obs.reset_event_log(None)
    obs.reset_slo_engine()
    obs.health.reset()
    yield
    obs.reset()
    obs.reset_event_log(from_env=True)
    obs.reset_slo_engine()
    obs.health.reset()


# -- a controllable fake engine (queue mechanics without device noise) ----
class _FakeHandle:
    trace_id = None

    def __init__(self, n, k, result_s=0.0):
        self._n, self._k, self._s = n, k, result_s

    def result(self):
        if self._s:
            time.sleep(self._s)
        return (np.zeros((self._n, self._k), np.float32),
                np.zeros((self._n, self._k), np.int64))


class _FakeEngine:
    """QueryQueue-facing engine stub: ``submit_s`` blocks the batcher
    (dispatch saturation), ``result_s`` blocks the completer."""

    buckets = BUCKETS

    def __init__(self, dim=DIM, submit_s=0.0, result_s=0.0):
        self._dim = dim
        self.submit_s = submit_s
        self.result_s = result_s

    def submit(self, cat, op="search"):
        if self.submit_s:
            time.sleep(self.submit_s)
        return _FakeHandle(cat.shape[0], K, self.result_s)

    def stats(self):
        return {"fake": True}


@pytest.fixture(scope="module")
def served():
    rng = np.random.default_rng(5)
    db = (rng.random((400, DIM)) * 10).astype(np.float32)
    q = (rng.random((64, DIM)) * 10).astype(np.float32)
    mesh = make_mesh(4, 2)
    prog = ShardedKNN(db, mesh=mesh, k=K)
    engine = ServingEngine(prog, buckets=BUCKETS)
    engine.warmup()
    return prog, engine, q


ROW = np.zeros((1, DIM), np.float32)


# -- bounded depth (the hook everything else builds on) -------------------
def test_max_depth_bounds_queue_growth_with_explicit_rejection():
    eng = _FakeEngine(submit_s=0.25)
    with QueryQueue(eng, max_wait_ms=0.0, max_depth=2) as q:
        f0 = q.submit(ROW)  # batcher grabs it, then blocks in submit_s
        time.sleep(0.05)  # pending drained, but f0 is still IN FLIGHT
        f1 = q.submit(ROW)
        # depth counts OUTSTANDING work (queued + in flight): f0 has
        # not completed, so the third submit finds 2 >= max_depth
        with pytest.raises(QueueFullError) as exc:
            q.submit(ROW)
        assert exc.value.reason == "queue_full"
        st = q.stats()
        assert st["admission"]["rejected"] == {"queue_full": 1}
        assert st["admission"]["admitted"] == 2
        # the accepted requests still complete normally, freeing slots
        for f in (f0, f1):
            f.result()
        time.sleep(0.05)  # completer retires the slots
        f2 = q.submit(ROW)  # depth back under the bound -> admitted
        f2.result()
    assert obs.counter(mn.ADMISSION_REJECTED, tenant="-",
                       reason="queue_full").get() == 1.0


def test_default_queue_remains_unbounded_regression():
    """Pre-admission behavior IS the default: no depth bound, no
    rejection, however deep the backlog grows (the regression guard:
    bounding is strictly opt-in)."""
    eng = _FakeEngine(submit_s=0.1)
    with QueryQueue(eng, max_wait_ms=50.0) as q:
        futs = [q.submit(ROW) for _ in range(100)]  # never raises
        st = q.stats()
        assert "admission" not in st  # pre-PR stats shape
        for f in futs:
            f.result()
        assert q.stats()["requests"] == 100


def test_conflicting_depth_bounds_raise():
    eng = _FakeEngine()
    with pytest.raises(ValueError, match="conflicting"):
        QueryQueue(eng, max_depth=4,
                   admission=AdmissionConfig(max_depth=8))
    # agreeing or one-sided specs are fine (merged)
    q = QueryQueue(eng, max_depth=4,
                   admission=AdmissionConfig(shed=True))
    assert q._ctrl.config.max_depth == 4
    assert q._ctrl.config.shed is True
    q.close()


# -- per-tenant quotas ----------------------------------------------------
def test_token_bucket_quota_rejects_over_rate_tenant():
    eng = _FakeEngine()
    cfg = AdmissionConfig(quotas={"a": (1.0, 2.0)})  # 1 q/s, burst 2
    with QueryQueue(eng, max_wait_ms=0.0, admission=cfg) as q:
        oks, rejs = 0, 0
        for _ in range(5):
            try:
                q.submit(ROW, tenant="a")
                oks += 1
            except QuotaExceededError as e:
                assert e.reason == "quota"
                rejs += 1
        assert (oks, rejs) == (2, 3)  # burst admits, then the wall
        # an unquota'd tenant is untouched by a's exhaustion
        for _ in range(5):
            q.submit(ROW, tenant="b")
        st = q.stats()["admission"]
        assert st["per_tenant"]["a"] == {"admitted": 2, "rejected": 3,
                                         "shed": 0}
        assert st["per_tenant"]["b"]["admitted"] == 5
    assert obs.counter(mn.ADMISSION_REJECTED, tenant="a",
                       reason="quota").get() == 3.0


def test_token_bucket_refills_over_time():
    now = [0.0]
    ctrl = AdmissionController(AdmissionConfig(quotas={"a": (10.0, 1.0)}))
    ctrl.admit(tenant="a", depth=0, rows=0,
               deadline_s=None, now=now[0])
    with pytest.raises(QuotaExceededError):
        ctrl.admit(tenant="a", depth=0, rows=0,
                   deadline_s=None, now=0.01)
    # 0.2 s at 10 tokens/s = 2 tokens accrued (capped at burst 1)
    ctrl.admit(tenant="a", depth=0, rows=0,
               deadline_s=None, now=0.2)


# -- deadline-aware shedding ----------------------------------------------
def test_submit_time_shed_uses_wait_estimate():
    ctrl = AdmissionController(AdmissionConfig(shed=True))
    # no estimator history yet: never shed on a fabricated estimate
    ctrl.admit(tenant=None, depth=0, rows=500,
               deadline_s=0.01, now=0.0)
    ctrl.observe_service(rows=100, seconds=1.0)  # 10 ms/row
    # 500 queued rows -> ~5 s wait; a 100 ms deadline cannot be met
    with pytest.raises(DeadlineError) as exc:
        ctrl.admit(tenant="t", depth=1, rows=500,
                   deadline_s=0.1, now=0.0)
    assert exc.value.reason == "deadline"
    # a 10 s deadline can
    ctrl.admit(tenant="t", depth=1, rows=500,
               deadline_s=10.0, now=0.0)
    assert obs.counter(mn.ADMISSION_REJECTED, tenant="t",
                       reason="deadline").get() == 1.0


def test_queued_requests_shed_on_expiry_before_dispatch():
    eng = _FakeEngine(submit_s=0.2)  # batcher saturated per dispatch
    cfg = AdmissionConfig(shed=True)
    with QueryQueue(eng, max_wait_ms=0.0, admission=cfg) as q:
        f0 = q.submit(ROW)  # occupies the batcher ~200 ms
        time.sleep(0.05)
        f1 = q.submit(ROW, deadline_ms=50.0)  # expires at ~100 ms
        f2 = q.submit(ROW)  # no deadline: must survive the sweep
        with pytest.raises(DeadlineError):
            f1.result(timeout=5)
        assert f2.result(timeout=5) is not None
        f0.result(timeout=5)
        st = q.stats()
        assert st["admission"]["shed"] == {"expired": 1}
        assert st["errors"] == 0  # a shed is an outcome, not an error
    assert obs.counter(mn.ADMISSION_SHED, tenant="-",
                       reason="expired").get() == 1.0


def test_deadline_rejection_never_spends_quota_token():
    """A request the deadline check sheds consumed zero capacity, so
    it must not drain the tenant's bucket — transient overload must
    not morph into spurious quota rejections after the drain."""
    ctrl = AdmissionController(
        AdmissionConfig(shed=True, quotas={"a": (1.0, 1.0)}))
    ctrl.observe_service(rows=10, seconds=1.0)  # 100 ms/row
    for _ in range(3):
        with pytest.raises(DeadlineError):
            ctrl.admit(tenant="a", depth=1, rows=100,
                       deadline_s=0.1, now=0.0)
    # the single burst token is still there: the first feasible
    # request after the overload is admitted, not quota-rejected
    ctrl.admit(tenant="a", depth=0, rows=0, deadline_s=100.0, now=0.0)


def test_expired_shed_delivered_promptly_under_large_max_wait():
    """The batcher's sleep is capped by the earliest pending deadline,
    not only the batch clock: a 10 s max-wait must not hold a 60 ms
    deadline's DeadlineError for 10 s."""
    eng = _FakeEngine()
    cfg = AdmissionConfig(shed=True)
    with QueryQueue(eng, max_wait_ms=10_000.0, admission=cfg) as q:
        t0 = time.monotonic()
        fut = q.submit(ROW, deadline_ms=60.0)
        with pytest.raises(DeadlineError):
            fut.result(timeout=5)
        assert time.monotonic() - t0 < 2.0  # promptly, not at max-wait


def test_default_deadline_applies_to_untagged_requests():
    ctrl = AdmissionController(
        AdmissionConfig(shed=True, default_deadline_ms=100.0))
    ctrl.observe_service(rows=10, seconds=1.0)  # 100 ms/row
    with pytest.raises(DeadlineError):
        # no explicit deadline -> the default one, unmeetable here
        ctrl.admit(tenant=None, depth=1, rows=100,
                   deadline_s=None, now=0.0)


# -- priority + starvation safety -----------------------------------------
def test_aged_priority_ordering_is_starvation_safe():
    eng = _FakeEngine()
    cfg = AdmissionConfig(priorities={"gold": 0, "free": 5},
                          aging_s=0.1)
    # a huge max-wait parks the batcher so _select_indices is
    # inspectable deterministically
    q = QueryQueue(eng, max_wait_ms=10_000.0, admission=cfg)
    try:
        q.submit(ROW, tenant="free")
        q.submit(ROW, tenant="gold")
        now = time.monotonic()
        order = [q._pending[i].tenant for i in q._select_indices(now)]
        # fresh: configured priority wins, arrival order loses
        assert order == ["gold", "free"]
        # age the free request one second: 10 levels of decay beats
        # gold's 5-level head start — no request starves forever
        q._pending[0].t_arr -= 1.0
        order = [q._pending[i].tenant for i in q._select_indices(now)]
        assert order == ["free", "gold"]
    finally:
        q.close()
    # the aging function itself is monotone: more wait, higher rank
    ctrl = AdmissionController(cfg)
    effs = [ctrl.effective_priority(5, w) for w in (0.0, 0.5, 1.0, 5.0)]
    assert effs == sorted(effs, reverse=True)
    assert ctrl.effective_priority(5, 1.0) < ctrl.effective_priority(
        0, 0.0)


def test_fifo_preserved_without_priorities():
    eng = _FakeEngine()
    q = QueryQueue(eng, max_wait_ms=10_000.0,
                   admission=AdmissionConfig(max_depth=100))
    try:
        for tenant in ("a", "b", "c"):
            q.submit(ROW, tenant=tenant)
        order = [q._pending[i].tenant
                 for i in q._select_indices(time.monotonic())]
        assert order == ["a", "b", "c"]
        # an explicit per-request priority= reorders even without a
        # configured tenant priority table (submit's documented
        # override contract)
        q.submit(ROW, tenant="d", priority=-1)
        order = [q._pending[i].tenant
                 for i in q._select_indices(time.monotonic())]
        assert order[0] == "d"
    finally:
        q.close()


# -- env configuration ----------------------------------------------------
def test_admission_config_from_env(monkeypatch):
    assert AdmissionConfig.from_env({}) is None  # no knobs -> disabled
    env = {
        "KNN_TPU_ADMISSION_MAX_DEPTH": "64",
        "KNN_TPU_ADMISSION_SHED": "1",
        "KNN_TPU_ADMISSION_DEFAULT_DEADLINE_MS": "250",
        "KNN_TPU_ADMISSION_QUOTAS": "gold:100:20, free:10",
        "KNN_TPU_ADMISSION_PRIORITIES": "gold:0,free:5",
        "KNN_TPU_ADMISSION_AGING_MS": "500",
    }
    cfg = AdmissionConfig.from_env(env)
    assert cfg.max_depth == 64
    assert cfg.shed is True
    assert cfg.default_deadline_ms == 250.0
    assert cfg.quotas == {"gold": (100.0, 20.0), "free": (10.0, 10.0)}
    assert cfg.priorities == {"gold": 0, "free": 5}
    assert cfg.aging_s == pytest.approx(0.5)
    with pytest.raises(ValueError, match="QUOTAS"):
        AdmissionConfig.from_env({"KNN_TPU_ADMISSION_QUOTAS": "bad"})
    with pytest.raises(ValueError, match="max_depth"):
        AdmissionConfig.from_env({"KNN_TPU_ADMISSION_MAX_DEPTH": "0"})
    # a typo'd knob must FAIL, not silently enable an unbounded config
    with pytest.raises(ValueError, match="unrecognized"):
        AdmissionConfig.from_env({"KNN_TPU_ADMISSION_MAX_DEPT": "64"})


def test_admission_config_validation():
    with pytest.raises(ValueError, match="quota"):
        AdmissionConfig(quotas={"a": (0.0, 1.0)}).validate()
    with pytest.raises(ValueError, match="aging_s"):
        AdmissionConfig(aging_s=0).validate()
    with pytest.raises(ValueError, match="default_deadline_ms"):
        AdmissionConfig(default_deadline_ms=-1).validate()


# -- per-tenant metrics + grouped SLOs ------------------------------------
def test_tenant_tagging_produces_per_tenant_series(served):
    prog, engine, qdata = served
    with QueryQueue(engine, max_wait_ms=1.0) as q:
        q.submit(qdata[:3], tenant="gold").result()
        q.submit(qdata[:2], tenant="free").result()
        q.submit(qdata[:2]).result()  # untagged: NO tenant series
    assert obs.counter(mn.TENANT_REQUESTS, tenant="gold").get() == 1.0
    assert obs.counter(mn.TENANT_REQUESTS, tenant="free").get() == 1.0
    snap = obs.snapshot()
    tenants = {s["labels"]["tenant"]
               for s in snap[mn.TENANT_REQUESTS]["series"]}
    assert tenants == {"gold", "free"}
    lat = {s["labels"]["tenant"]: s["value"]
           for s in snap[mn.TENANT_REQUEST_LATENCY]["series"]}
    assert lat["gold"]["count"] == 1 and lat["gold"]["sum"] > 0
    # direct engine submissions tag the same family
    engine.submit(qdata[:2], tenant="gold").result()
    assert obs.counter(mn.TENANT_REQUESTS, tenant="gold").get() == 2.0


def test_grouped_slo_fires_per_tenant_not_globally():
    eng = slo.SLOEngine()
    eng.evaluate(now=0.0)  # baseline counter sample BEFORE the burst
    obs.counter(mn.TENANT_REQUESTS, tenant="a").inc(100)
    obs.counter(mn.TENANT_ERRORS, tenant="a").inc(50)
    obs.counter(mn.TENANT_REQUESTS, tenant="b").inc(100)
    rep = eng.evaluate(now=300.0)
    entry = rep["objectives"]["tenant_availability"]
    assert entry["group_by"] == "tenant"
    assert entry["breached"] == ["a"]  # b is healthy
    assert rep["breached"] == ["tenant_availability:a"]
    assert entry["groups"]["a"]["windows"]["slow"]["burn_rate"] > 6
    assert entry["groups"]["b"]["breached"] is False
    # the alert is edge-triggered, per tenant, and carries the tenant
    alerts = [e for e in obs.get_event_log().recent()
              if e.get("name") == "slo.alert"]
    assert [(a["objective"], a["state"], a.get("tenant"))
            for a in alerts] == [("tenant_availability:a", "firing", "a")]
    assert obs.gauge(mn.SLO_BREACHED,
                     objective="tenant_availability:a").get() == 1.0
    assert obs.gauge(mn.SLO_BREACHED,
                     objective="tenant_availability:b").get() == 0.0
    # recovery clears exactly a's breach
    obs.counter(mn.TENANT_REQUESTS, tenant="a").inc(5000)
    rep = eng.evaluate(now=900.0)
    assert rep["breached"] == []
    states = [(a["objective"], a["state"]) for a in
              obs.get_event_log().recent() if a.get("name") == "slo.alert"]
    assert states == [("tenant_availability:a", "firing"),
                      ("tenant_availability:a", "resolved")]


def test_errors_without_request_growth_breach_instead_of_hiding():
    """A tenant whose every request fails before the success-side
    counter increments (errors grow, requests don't) must read as the
    worst ratio, not as healthy-by-division-by-zero."""
    eng = slo.SLOEngine()
    eng.evaluate(now=0.0)
    obs.counter(mn.TENANT_ERRORS, tenant="broken").inc(50)
    rep = eng.evaluate(now=300.0)
    entry = rep["objectives"]["tenant_availability"]
    assert entry["breached"] == ["broken"]
    assert rep["breached"] == ["tenant_availability:broken"]


def test_grouped_quantile_slo_per_tenant():
    eng = slo.SLOEngine()
    h = obs.histogram(mn.TENANT_REQUEST_LATENCY, tenant="slowpoke")
    for _ in range(20):
        h.observe(3.0)  # p99 3 s >> 1 s threshold
    obs.histogram(mn.TENANT_REQUEST_LATENCY, tenant="quick").observe(0.01)
    rep = eng.evaluate(now=0.0)
    entry = rep["objectives"]["tenant_request_p99"]
    assert entry["breached"] == ["slowpoke"]
    assert entry["groups"]["slowpoke"]["value_s"] == pytest.approx(3.0)
    assert entry["groups"]["quick"]["breached"] is False
    # the doctor/statusz text renders grouped objectives per tenant
    # (not the ungrouped-shape garbage lines)
    text = obs.health.render_text({"slo": rep})
    assert "tenant_request_p99 (per tenant): 1/2 breached" in text
    assert "tenant_request_p99:slowpoke: BREACHED" in text
    assert "tenant_request_p99:quick: ok" in text
    assert "burn={}" not in text and "None=Nones" not in text
    # idle grouped objectives render as a quiet one-liner
    idle = obs.health.render_text(
        {"slo": {"objectives": {"tenant_availability": {
            "kind": "ratio", "group_by": "tenant", "groups": {},
            "breached": []}}}})
    assert "tenant_availability: no tenant traffic" in idle


# -- disabled-mode bitwise identity ---------------------------------------
def test_admission_off_is_bitwise_identical_prepr_behavior(served):
    """The contract the whole PR hangs off: a default-built queue has
    the pre-admission stats() shape, produces bitwise-identical
    results, and mints NO admission/tenant metric series."""
    prog, engine, qdata = served
    with QueryQueue(engine, max_wait_ms=1.0) as q:
        d_q, i_q = q.submit(qdata[:5]).result()
    # bitwise vs the engine's own bucketed dispatch of the same rows
    d_e, i_e = engine.submit(qdata[:5]).result()
    assert np.array_equal(d_q, d_e) and np.array_equal(i_q, i_e)
    st = q.stats()
    assert set(st) == {"requests", "dispatches", "coalesced_rows",
                       "errors", "latency_ms", "engine"}
    snap = obs.snapshot()
    assert not any(name.startswith(("knn_tpu_admission_",
                                    "knn_tpu_tenant_"))
                   for name in snap)
    # engine stats shape untouched either (no admission section)
    assert "admission" not in engine.stats()


# -- the brownout acceptance ----------------------------------------------
def test_brownout_sheds_holds_slo_serves_both_tenants_and_recovers(served):
    """At ~5x the measured closed-loop capacity the admission-enabled
    queue sheds with explicit outcomes while ADMITTED p99 stays within
    the SLO and both tenants keep being served; after the burst a
    normal-rate run recovers — shed, don't collapse."""
    prog, engine, qdata = served
    # closed-loop anchor: the rate one-at-a-time round trips sustain
    with QueryQueue(engine, max_wait_ms=1.0) as q0:
        t0 = time.monotonic()
        futs = [q0.submit(qdata[:2]) for _ in range(24)]
        for f in futs:
            f.result()
        anchor = 24 / (time.monotonic() - t0)
    deadline_ms = 100.0
    slo_ms = 400.0  # deadline + generous service/CI slack
    cfg = AdmissionConfig(
        max_depth=16, shed=True,
        # finite but per-tenant-fair quotas: each tenant may use up to
        # ~60% of capacity, so neither can crowd the other out
        quotas={"gold": (max(1.5, 0.6 * anchor), max(4.0, anchor / 4)),
                "free": (max(1.5, 0.6 * anchor), max(4.0, anchor / 4))},
        priorities={"gold": 0, "free": 2}, aging_s=0.05)
    tenants = (
        loadgen.TenantSpec("gold", weight=1, batch_sizes=(1, 2),
                           deadline_ms=deadline_ms, priority=0),
        loadgen.TenantSpec("free", weight=1, batch_sizes=(1, 2),
                           deadline_ms=deadline_ms, priority=2),
    )
    burst = loadgen.WorkloadSpec(rate_qps=5 * anchor, duration_s=1.0,
                                 seed=21, tenants=tenants)
    with QueryQueue(engine, max_wait_ms=1.0, admission=cfg) as q:
        rep = loadgen.run_workload(q, loadgen.generate(burst),
                                   queries=qdata, submitters=4,
                                   waiters=4)
        # overload produced explicit outcomes, not a collapse
        assert rep["rejected"] + rep["shed"] > 0
        declined = {k: v for k, v in rep["outcomes"].items()
                    if k != "ok"}
        assert all(k.startswith(("rejected:", "shed:"))
                   for k in declined), declined
        # admitted requests kept their tail: the whole point of
        # shedding is that the survivors' latency story holds
        assert rep["ok"] > 0
        assert rep["latency_ms"]["p99"] <= slo_ms
        # no tenant starved: both kept completing under overload
        for tenant in ("gold", "free"):
            assert rep["per_tenant"][tenant]["ok"] > 0, rep["per_tenant"]
        # the burst ENDS: wait for the in-flight backlog to drain (the
        # recovery claim is about post-burst behavior, not about racing
        # the tail of the burst through a still-full depth bound)
        for _ in range(200):
            if q._out_req == 0:
                break
            time.sleep(0.05)
        assert q._out_req == 0  # cleanly drained, nothing wedged
        # recovery on the SAME queue: calm traffic flows again.  The
        # closed-loop anchor over-estimates open-loop capacity (burst
        # probes coalesce maximally), so "calm" is well below it.
        calm_tenants = tuple(
            loadgen.TenantSpec(t.name, weight=t.weight,
                               batch_sizes=t.batch_sizes,
                               deadline_ms=slo_ms, priority=t.priority)
            for t in tenants)
        calm = loadgen.WorkloadSpec(rate_qps=0.2 * anchor,
                                    duration_s=0.8, seed=22,
                                    tenants=calm_tenants)
        rep2 = loadgen.run_workload(q, loadgen.generate(calm),
                                    queries=qdata, submitters=2,
                                    waiters=2)
        assert rep2["ok"] >= 0.6 * rep2["offered"], rep2["outcomes"]
        assert rep2["latency_ms"]["p99"] <= slo_ms
        st = q.stats()["admission"]
        assert st["admitted"] == rep["ok"] + rep2["ok"] + rep["shed"] \
            + rep2["shed"] + rep["errors"] + rep2["errors"]
    # admission surfaced through the catalog metrics
    snap = obs.snapshot()
    assert mn.ADMISSION_ADMITTED in snap
    assert any(name in snap for name in (mn.ADMISSION_REJECTED,
                                         mn.ADMISSION_SHED))
