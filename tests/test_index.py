"""Mutable index (knn_tpu.index): the pinned mutation oracle —
insert-then-search bitwise vs a rebuilt-from-scratch index across
precisions and kernels — delete-mask certified soundness, compaction-
swap atomicity under the 8-thread hammer, epoch visibility, zero
recompilation during steady-state mutation, loud refusals on the
placements mutation cannot cover, obs on/off bitwise identity, and the
live mixed-traffic proof: flat admitted p99 and zero SLO burn across
background compaction swaps with complete waterfalls."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from knn_tpu import loadgen, obs
from knn_tpu.index.artifact import (
    MutationBudgetError,
    MutationUnsupportedError,
    validate_mutation_block,
)
from knn_tpu.index.mutable import MutableIndex
from knn_tpu.obs import names as mn, waterfall
from knn_tpu.parallel.mesh import make_mesh

REPO = __file__.rsplit("/tests/", 1)[0]

DIM = 12
K = 5


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset(enabled=True)
    obs.reset_event_log(None)
    obs.reset_slo_engine()
    obs.health.reset()
    yield
    obs.reset()
    obs.reset_event_log(from_env=True)
    obs.reset_slo_engine()
    obs.health.reset()


def _f64_oracle(rows, ids, q, k=K):
    """Independent float64 ranking (identity/allclose checks; the
    BITWISE pin is mutated-vs-fresh through the index itself)."""
    d = ((rows.astype(np.float64)[None]
          - q.astype(np.float64)[:, None]) ** 2).sum(-1)
    pos = np.broadcast_to(np.arange(rows.shape[0]), d.shape)
    o = np.lexsort((pos, d), axis=-1)[:, :k]
    return np.take_along_axis(d, o, -1), ids[o]


@pytest.fixture(scope="module")
def scenario():
    """One mutated index + the fresh-from-survivors oracle index, built
    once for every certified-bitwise parametrization."""
    rng = np.random.default_rng(7)
    db = rng.normal(size=(1500, DIM)).astype(np.float32) * 20
    q = rng.normal(size=(9, DIM)).astype(np.float32) * 20
    mesh = make_mesh(2, 4)
    idx = MutableIndex(db, mesh=mesh, k=K, reserve=4)
    new = rng.normal(size=(6, DIM)).astype(np.float32) * 20
    idx.insert(new[:4], np.arange(9000, 9004))
    idx.insert(new[4:], np.arange(9004, 9006))
    dead = [3, 250, 1499]
    idx.delete(dead)
    surv = np.ones(1500, bool)
    surv[dead] = False
    rows = np.concatenate([db[surv], new])
    ids = np.concatenate([np.arange(1500)[surv],
                          np.arange(9000, 9006)])
    fresh = MutableIndex(rows, ids, mesh=mesh, k=K, reserve=4)
    return {"idx": idx, "fresh": fresh, "q": q, "db": db, "new": new,
            "rows": rows, "ids": ids, "dead": dead, "mesh": mesh}


# -- the pinned mutation oracle -------------------------------------------
@pytest.mark.parametrize("precision", ["highest", "bf16x3", "int8"])
@pytest.mark.parametrize("kernel", ["tiled", "streaming", "fused"])
def test_mutation_oracle_bitwise_pallas(scenario, precision, kernel):
    """After inserts + deletes, search_certified is BITWISE-identical
    to a fresh index built from the surviving rows — per coarse
    precision x kernel (the acceptance pin)."""
    kw = dict(selector="pallas", margin=8, tile_n=256,
              precision=precision, kernel=kernel)
    d_m, i_m, st = scenario["idx"].search_certified(scenario["q"], **kw)
    d_f, i_f, _ = scenario["fresh"].search_certified(scenario["q"], **kw)
    np.testing.assert_array_equal(d_m, d_f)
    np.testing.assert_array_equal(i_m, i_f)
    assert st["index"]["tail_rows"] == 6
    assert st["index"]["tombstones"] == 3
    # and both match the independent f64 ranking (identity, not bits)
    od, oi = _f64_oracle(scenario["rows"], scenario["ids"],
                         scenario["q"])
    np.testing.assert_array_equal(i_m, oi)
    np.testing.assert_allclose(d_m, od, rtol=1e-12)


@pytest.mark.parametrize("selector", ["approx", "exact"])
def test_mutation_oracle_bitwise_counted(scenario, selector):
    d_m, i_m, _ = scenario["idx"].search_certified(
        scenario["q"], selector=selector)
    d_f, i_f, _ = scenario["fresh"].search_certified(
        scenario["q"], selector=selector)
    np.testing.assert_array_equal(d_m, d_f)
    np.testing.assert_array_equal(i_m, i_f)


def test_oracle_survives_compaction_and_carryover(scenario):
    """Compact mid-stream, keep mutating, and the oracle still holds:
    carried-over writes land against the new epoch."""
    rng = np.random.default_rng(11)
    mesh = scenario["mesh"]
    idx = MutableIndex(scenario["db"], mesh=mesh, k=K, reserve=4)
    idx.insert(scenario["new"], np.arange(9000, 9006))
    idx.delete([3, 250])
    assert idx.compact()["epoch"] == 1
    extra = rng.normal(size=(2, DIM)).astype(np.float32) * 20
    idx.insert(extra, [9100, 9101])
    idx.delete([1499, 9001])
    surv0 = np.ones(1500, bool)
    surv0[[3, 250, 1499]] = False
    keep_new = np.ones(6, bool)
    keep_new[1] = False  # id 9001
    rows = np.concatenate([scenario["db"][surv0],
                           scenario["new"][keep_new], extra])
    ids = np.concatenate([np.arange(1500)[surv0],
                          np.arange(9000, 9006)[keep_new],
                          [9100, 9101]])
    fresh = MutableIndex(rows, ids, mesh=mesh, k=K, reserve=4)
    for kw in (dict(selector="approx"),
               dict(selector="pallas", margin=8, tile_n=256,
                    kernel="streaming")):
        d_m, i_m, _ = idx.search_certified(scenario["q"], **kw)
        d_f, i_f, _ = fresh.search_certified(scenario["q"], **kw)
        np.testing.assert_array_equal(d_m, d_f)
        np.testing.assert_array_equal(i_m, i_f)


# -- delete-mask certified soundness --------------------------------------
def test_delete_mask_certified_soundness(rng):
    """Deleting the nearest neighbors promotes exactly the next live
    rows — certified, and never a tombstoned id."""
    db = rng.normal(size=(600, DIM)).astype(np.float32) * 10
    q = rng.normal(size=(7, DIM)).astype(np.float32) * 10
    idx = MutableIndex(db, mesh=make_mesh(4, 2), k=K, reserve=8)
    _, i0, _ = idx.search_certified(q)
    dead = sorted({int(i0[r, 0]) for r in range(3)})
    idx.delete(dead)
    d, i, _ = idx.search_certified(q)
    assert not np.isin(i, np.asarray(dead)).any()
    surv = np.ones(600, bool)
    surv[dead] = False
    od, oi = _f64_oracle(db[surv], np.arange(600)[surv], q)
    np.testing.assert_array_equal(i, oi)
    np.testing.assert_allclose(d, od, rtol=1e-12)
    # plain search masks identically (neighbor identity)
    _, ip = idx.search(q)
    np.testing.assert_array_equal(ip, oi)


def test_epoch_visibility_and_write_then_read(rng):
    db = rng.normal(size=(400, DIM)).astype(np.float32)
    q = rng.normal(size=(4, DIM)).astype(np.float32)
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8)
    assert idx.epoch == 0
    # a row guaranteed nearest to q[0]: the query itself
    idx.insert(q[:1], [7000])
    _, i = idx.search(q)
    assert i[0, 0] == 7000, "insert must be visible to the next search"
    idx.delete([7000])
    _, i = idx.search(q)
    assert not (i == 7000).any(), "delete must be visible immediately"
    idx.compact()
    assert idx.epoch == 1
    _, i2 = idx.search(q)
    np.testing.assert_array_equal(i, i2)
    st = idx.stats()
    assert st["tail_rows"] == 0 and st["tombstones"] == 0
    assert st["compactions"] == 1


# -- budgets & refusals ----------------------------------------------------
def test_budget_refusals_and_id_rules(rng):
    db = rng.normal(size=(300, DIM)).astype(np.float32)
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=4,
                       delta_min_rows=64, delta_max_rows=128)
    # duplicate live id
    with pytest.raises(ValueError, match="already live"):
        idx.insert(db[:1], [5])
    # unknown delete
    with pytest.raises(KeyError):
        idx.delete([12345])
    # tombstone budget = reserve
    idx.delete([0, 1, 2, 3])
    with pytest.raises(MutationBudgetError, match="compact"):
        idx.delete([4])
    # re-inserting a tombstoned id is refused until compaction
    with pytest.raises(ValueError, match="compact"):
        idx.insert(db[:1], [0])
    idx.compact()
    idx.insert(db[:1], [0])  # id freed by the swap
    # tail capacity wall
    big = rng.normal(size=(128, DIM)).astype(np.float32)
    with pytest.raises(MutationBudgetError, match="ladder"):
        idx.insert(big, np.arange(20000, 20128))


def test_refusals_host_tier_multihost_and_metric(rng):
    db = rng.normal(size=(4096, DIM)).astype(np.float32)
    # host-tier placement: construction is fine, mutation refuses
    from knn_tpu.analysis import hbm

    budget = hbm.placement_bytes(1024, DIM, 4)
    idx = MutableIndex(db, mesh=make_mesh(), k=K,
                       hbm_budget_bytes=budget)
    with pytest.raises(MutationUnsupportedError, match="host-RAM"):
        idx.insert(db[:1], [90001])
    with pytest.raises(MutationUnsupportedError, match="host-RAM"):
        idx.delete([0])
    # multi-host (hierarchical) mesh
    from knn_tpu.parallel.mesh import make_host_mesh

    hidx = MutableIndex(db[:512], mesh=make_host_mesh(2, 2, 2), k=K)
    with pytest.raises(MutationUnsupportedError, match="multi-host"):
        hidx.insert(db[:1], [90001])
    # MultiHostKNN replicas refuse with the documented error
    from knn_tpu.parallel.multihost import MultiHostKNN

    mh = MultiHostKNN.__new__(MultiHostKNN)
    mh.process_count = 2
    with pytest.raises(MutationUnsupportedError, match="replication"):
        mh.insert(vectors=db[:1], ids=[1])
    with pytest.raises(MutationUnsupportedError, match="replication"):
        mh.delete(ids=[1])
    # unsupported metrics refuse at construction
    with pytest.raises(MutationUnsupportedError, match="l2"):
        MutableIndex(db, mesh=make_mesh(), k=K, metric="cosine")


# -- compaction-swap atomicity under the hammer ---------------------------
@pytest.mark.skipif(
    len(os.sched_getaffinity(0)) < 2,
    reason="8 concurrent eager-dispatch readers deadlock the "
           "single-threaded XLA CPU client when the process is pinned "
           "to one core (reproduced on the unmodified seed); the "
           "hammer needs real thread parallelism to mean anything")
def test_compaction_swap_atomicity_hammer(rng):
    """8 reader threads against repeated swaps: every result equals the
    (mutation-free) baseline — no torn snapshot, no exception."""
    db = rng.normal(size=(500, DIM)).astype(np.float32) * 10
    q = rng.normal(size=(6, DIM)).astype(np.float32) * 10
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8)
    _, base_ids = idx.search(q)
    errors, mismatches = [], []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                _, i = idx.search(q)
                if not np.array_equal(i, base_ids):
                    mismatches.append(i)
            except Exception as e:  # noqa: BLE001 — the hammer's verdict
                errors.append(e)
                return

    threads = [threading.Thread(target=reader) for _ in range(8)]
    for t in threads:
        t.start()
    for _ in range(4):
        idx.compact()  # no pending writes: results must be invariant
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[:1]
    assert not mismatches, "a search observed a half-swapped state"
    assert idx.epoch == 4


# -- obs on/off bitwise ----------------------------------------------------
def test_obs_on_off_bitwise(rng):
    db = rng.normal(size=(400, DIM)).astype(np.float32)
    new = rng.normal(size=(3, DIM)).astype(np.float32)
    q = rng.normal(size=(5, DIM)).astype(np.float32)

    def run():
        idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8)
        idx.insert(new, [8000, 8001, 8002])
        idx.delete([7])
        d1, i1 = idx.search(q)
        d2, i2, _ = idx.search_certified(q)
        idx.compact()
        d3, i3, _ = idx.search_certified(q)
        return d1, i1, d2, i2, d3, i3

    on = run()
    assert obs.counter(mn.INDEX_COMPACTIONS).get() == 1.0
    assert obs.gauge(mn.INDEX_EPOCH).get() == 1.0
    obs.reset(enabled=False)
    off = run()
    assert obs.snapshot() == {}
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


# -- zero recompilation during steady-state mutation ----------------------
def test_zero_recompile_steady_state(rng):
    """Compile counters stay FLAT while the tail grows within its
    ladder rung and tombstones accrue — the zero-recompilation pin."""
    db = rng.normal(size=(400, DIM)).astype(np.float32)
    q = rng.normal(size=(4, DIM)).astype(np.float32)
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8,
                       delta_min_rows=64)
    eng = idx.serving_engine(buckets=(8, 16))
    eng.warmup()
    # one mutation + search warms the tail path's real shapes
    idx.insert(rng.normal(size=(2, DIM)).astype(np.float32),
               [8000, 8001])
    eng.search(q)
    idx.search(q)
    jax_compiles0 = sum(
        s["value"] for s in obs.snapshot().get(
            mn.JAX_COMPILES, {}).get("series", []))
    engine_compiles0 = eng.stats()["compile_count"]
    for j in range(6):  # stays inside the 64-row first rung
        idx.insert(rng.normal(size=(3, DIM)).astype(np.float32),
                   np.arange(9000 + 10 * j, 9003 + 10 * j))
        if j % 2:
            idx.delete([9000 + 10 * j])
        eng.search(q)
        idx.search(q)
    jax_compiles1 = sum(
        s["value"] for s in obs.snapshot().get(
            mn.JAX_COMPILES, {}).get("series", []))
    assert eng.stats()["compile_count"] == engine_compiles0
    assert jax_compiles1 == jax_compiles0, (
        f"XLA compiled during steady-state mutation "
        f"({jax_compiles0} -> {jax_compiles1})")


# -- serving integration ---------------------------------------------------
def test_serving_engine_matches_direct_and_stats(rng):
    db = rng.normal(size=(500, DIM)).astype(np.float32) * 10
    q = rng.normal(size=(6, DIM)).astype(np.float32) * 10
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8)
    eng = idx.serving_engine(buckets=(8, 16))
    eng.warmup()
    idx.insert(q[:2] + 0.01, [8000, 8001])  # near-certain top hits
    idx.delete([0, 1])
    d_e, i_e = eng.search(q)
    d_d, i_d = idx.search(q)
    np.testing.assert_array_equal(i_e, i_d)
    assert d_e.shape == (6, K)
    st = eng.stats()
    assert st["index"]["tail_rows"] == 2
    assert st["index"]["tombstones"] == 2
    with pytest.raises(ValueError, match="search"):
        eng.submit(q, op="predict")
    # second serving engine on the same index is refused (one home)
    with pytest.raises(RuntimeError, match="already"):
        idx.serving_engine(buckets=(8,))


def test_queue_submit_write_first_class(rng):
    from knn_tpu.serving.engine import ServingEngine
    from knn_tpu.serving.queue import QueryQueue

    db = rng.normal(size=(400, DIM)).astype(np.float32)
    q = rng.normal(size=(3, DIM)).astype(np.float32)
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=8)
    eng = idx.serving_engine(buckets=(8, 16))
    eng.warmup()
    with QueryQueue(eng, max_wait_ms=1.0) as qq:
        f1 = qq.submit_write("insert", vectors=q[:1], ids=[8000],
                             tenant="w")
        assert f1.result()["tail_rows"] == 1
        f2 = qq.submit_write("delete", ids=[8000])
        assert f2.result()["tombstones"] == 1
        bad = qq.submit_write("delete", ids=[999999])
        with pytest.raises(KeyError):
            bad.result()
        _, ids = qq.submit(q).result()
        assert not (ids == 8000).any()
        st = qq.stats()
        assert st["writes"] == {"insert": 1, "delete": 1, "errors": 1}
    # a plain immutable engine refuses writes loudly
    from knn_tpu.parallel.sharded import ShardedKNN

    plain = ServingEngine(ShardedKNN(db, mesh=make_mesh(), k=K),
                          buckets=(8,))
    with QueryQueue(plain, max_wait_ms=1.0) as qq2:
        with pytest.raises(ValueError, match="immutable"):
            qq2.submit_write("insert", vectors=q[:1], ids=[1])
        assert "writes" not in qq2.stats()  # write-free shape pinned


def test_compactor_thresholds_fire(rng):
    db = rng.normal(size=(300, DIM)).astype(np.float32)
    with MutableIndex(db, mesh=make_mesh(), k=K, reserve=8,
                      compact_tail_rows=4) as idx:
        idx.start_compactor()
        idx.insert(rng.normal(size=(5, DIM)).astype(np.float32),
                   np.arange(8000, 8005))
        deadline = time.monotonic() + 30
        while idx.stats()["compactions"] < 1:
            assert time.monotonic() < deadline, "compactor never fired"
            time.sleep(0.02)
        st = idx.stats()
        assert st["epoch"] >= 1 and st["rows"] == 305


# -- the live mixed-traffic proof -----------------------------------------
def test_live_mixed_traffic_flat_p99_across_swaps(rng):
    """The ROADMAP acceptance bar: a loadgen read+write mix on a REAL
    engine shows flat admitted p99 and zero SLO burn across >= 2
    background compaction swaps, with waterfalls proving swaps never
    stall the queue (every admitted read tiles completely)."""
    from knn_tpu.serving.queue import QueryQueue

    db = rng.normal(size=(400, DIM)).astype(np.float32)
    pool = rng.normal(size=(64, DIM)).astype(np.float32)
    idx = MutableIndex(db, mesh=make_mesh(), k=K, reserve=16,
                       compact_tail_rows=6)
    eng = idx.serving_engine(buckets=(8, 16))
    eng.warmup()
    idx.start_compactor()
    spec = loadgen.WorkloadSpec(
        rate_qps=150, duration_s=1.2, seed=13,
        tenants=(
            loadgen.TenantSpec("readers", weight=0.8,
                               batch_sizes=(1, 2, 4)),
            loadgen.TenantSpec("writers", weight=0.2, batch_sizes=(1,),
                               insert_fraction=0.6,
                               delete_fraction=0.3),
        ))
    reqs = loadgen.generate(spec)
    assert any(r.kind == "insert" for r in reqs)
    try:
        with QueryQueue(eng, max_wait_ms=2.0) as qq:
            rep = loadgen.run_workload(qq, reqs, queries=pool,
                                       include_records=True)
    finally:
        idx.close()
    swaps = idx.stats()["compactions"]
    assert swaps >= 2, f"only {swaps} compaction swap(s) happened"
    # write stream really ran, and cleanly
    assert rep["writes"]["insert"].get("ok", 0) >= 6
    assert rep["writes"].get("total", 0) > 0
    assert rep["errors"] == 0, rep["outcomes"]
    # flat admitted p99: finite, bounded, and no worse late (after the
    # swaps) than a generous multiple of the whole-run p99
    lat = rep["latency_ms"]
    assert lat and lat["p99"] < 500.0, lat
    recs = [r for r in rep["records"]
            if r.get("kind", "query") == "query"
            and r["outcome"] == "ok"]
    assert len(recs) >= 50
    mid = sorted(r["completion_s"] for r in recs)[len(recs) // 2]
    late = [r["latency_s"] * 1e3 for r in recs
            if r["completion_s"] >= mid]
    assert np.percentile(late, 99) < 500.0
    # zero SLO burn: one evaluation pass, nothing breached, no
    # edge-triggered transition fired during the run
    slo_rep = obs.slo_report()
    assert slo_rep.get("breached", []) == []
    transitions = sum(
        s["value"] for s in obs.snapshot().get(
            mn.SLO_BREACH_TRANSITIONS, {}).get("series", []))
    assert transitions == 0
    # waterfalls: every admitted read that still reconstructs from the
    # bounded ring tiles completely — swaps never left a stall gap
    wfs = waterfall.reconstruct(obs.get_event_log().recent())
    checked, bad = 0, []
    for r in recs:
        w = wfs.get(r.get("trace_id"))
        if w is None:
            continue  # rotated out of the bounded ring
        checked += 1
        # no queue stall coincident with swaps: NO request may carry a
        # large unattributed gap (the stall signature), and nearly all
        # must tile completely — a bounded allowance for sub-stall GIL
        # hiccups the CPU harness's background compiles can inject
        # into the few span-free microseconds of a request's life
        assert w["unattributed_s"] < 0.1, w
        if not w["complete"]:
            bad.append({k: w.get(k) for k in (
                "trace_id", "total_s", "unattributed_s", "overlap_s",
                "tolerance_s", "segments")})
    assert checked >= 20
    assert len(bad) <= max(1, checked // 20), \
        json.dumps(bad, default=str)[:2000]
    # the compaction spans are attributable beside the request spans
    compact_spans = [e for e in obs.get_event_log().recent()
                     if e.get("span") == "index.compact"
                     or e.get("name") == "index.compact"]
    assert len(compact_spans) >= 2


# -- artifact validator + refresher inputs --------------------------------
def test_mutation_block_validator():
    good = {
        "mutation_version": 1,
        "write_mix": {"insert_fraction": 0.1, "delete_fraction": 0.05},
        "rate_qps": 200.0, "duration_s": 2.0,
        "admitted_p99_ms": 12.5, "compactions": 2, "epoch": 2,
        "reads": {"offered": 380, "ok": 380},
        "writes": {"insert": {"ok": 40}},
        "slo_breach_transitions": 0,
    }
    assert validate_mutation_block(good) == []
    assert validate_mutation_block({"error": "boom"}) == []
    bad = dict(good, mutation_version=2)
    assert any("mutation_version" in e
               for e in validate_mutation_block(bad))
    bad = dict(good)
    del bad["writes"]
    assert any("writes" in e for e in validate_mutation_block(bad))
    bad = dict(good, compactions=0)
    assert any("compactions" in e for e in validate_mutation_block(bad))
    assert validate_mutation_block(
        dict(good, compactions=0, compactions_waived=True)) == []
    bad = dict(good, write_mix={"insert_fraction": 2.0,
                                "delete_fraction": 0.0})
    assert any("insert_fraction" in e
               for e in validate_mutation_block(bad))


@pytest.mark.slow
def test_cli_index_selftest_subprocess():
    r = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "index", "--selftest"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"})
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ok"] and out["oracle_bitwise"]


def test_cli_index_snapshot_render(tmp_path):
    """The jax-free status surface: renders the index section from a
    snapshot (exit 0) and says so when none is registered (exit 2)."""
    snap = tmp_path / "snap.json"
    snap.write_text(json.dumps({"health": {
        "readiness": {"ready": True, "reasons": []},
        "index": [{"epoch": 3, "rows": 100, "tail_rows": 2,
                   "tail_capacity": 64, "tombstones": 1, "budget": 8,
                   "live_rows": 101, "compactions": 3}]}}))
    r = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "index",
         "--snapshot", str(snap)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr + r.stdout
    assert "epoch=3" in r.stdout and "compactions=3" in r.stdout
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"health": {
        "readiness": {"ready": False, "reasons": []}, "index": []}}))
    r2 = subprocess.run(
        [sys.executable, "-m", "knn_tpu.cli", "index",
         "--snapshot", str(empty)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r2.returncode == 2
    assert "no mutable index registered" in r2.stdout
