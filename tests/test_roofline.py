"""The roofline cost model (knn_tpu.obs.roofline): byte terms pinned
against the ACTUAL kernel operand arrays' nbytes, ceilings that bound
real interpret-mode runs, the pinned r05 SIFT1M bound-class
attribution, the tuning-cache version bump, registry publication, and
the obs-off no-op — the acceptance surface of the roofline ISSUE."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from knn_tpu import obs, tuning
from knn_tpu.obs import health, roofline, sentinel
from knn_tpu.obs import names as mn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_roofline_store():
    roofline.reset()
    yield
    roofline.reset()
    obs.reset()
    health.reset()


# --- byte counts vs actual operand nbytes ------------------------------


def _actual_operand_nbytes(db, precision):
    """Build the db-side operand arrays exactly as
    ops.pallas_knn._bin_candidates does and return their real nbytes."""
    n = db.shape[0]
    if precision == "bf16x3":
        th = db.astype(jnp.bfloat16)
        tl = (db - th.astype(jnp.float32)).astype(jnp.bfloat16)
        values = th.nbytes + tl.nbytes
        aux = jnp.broadcast_to(
            jnp.sum(db * db, axis=-1)[None, :], (8, n)).nbytes
    elif precision == "bf16x3f":
        th = db.astype(jnp.bfloat16)
        tl = (db - th.astype(jnp.float32)).astype(jnp.bfloat16)
        t3 = jnp.concatenate([th, tl, th], axis=1)
        values = t3.nbytes
        aux = jnp.broadcast_to(
            jnp.sum(db * db, axis=-1)[None, :], (8, n)).nbytes
    elif precision == "int8":
        from knn_tpu.ops.quantize import quantize_rows

        ti, ts = quantize_rows(db)
        values = ti.nbytes
        tn = jnp.sum(db * db, axis=-1)
        aux = jnp.concatenate([
            jnp.broadcast_to(tn[None, :], (8, n)),
            jnp.broadcast_to(ts[None, :].astype(jnp.float32), (8, n)),
        ], axis=0).nbytes
    elif precision == "int4":
        from knn_tpu.ops.quantize import pack_nibbles_t, quantize_rows_int4

        tq, ts = quantize_rows_int4(db)
        values = pack_nibbles_t(tq).nbytes
        # norms row 0, scales row 1, zero fill rows 2-7: the ONE 8-row
        # aux block (kernel reads one row of each; no broadcast)
        aux = jnp.concatenate([
            jnp.sum(db * db, axis=-1)[None, :],
            ts[None, :].astype(jnp.float32),
            jnp.zeros((6, n), jnp.float32),
        ], axis=0).nbytes
    elif precision == "pq":
        # the streamed operand is the [N, ceil(d/dsub)] uint8 code
        # array (shape-determined — training moves no extra bytes)
        # plus the 8-row pad-fill carrier
        m_sub = -(-db.shape[1] // 4)
        values = jnp.zeros((n, m_sub), jnp.uint8).nbytes
        aux = jnp.broadcast_to(
            jnp.zeros((n,), jnp.float32)[None, :], (8, n)).nbytes
    else:  # highest / default stream the raw f32 rows
        values = db.astype(jnp.float32).nbytes
        aux = jnp.broadcast_to(
            jnp.sum(db * db, axis=-1)[None, :], (8, n)).nbytes
    return int(values), int(aux)


@pytest.mark.parametrize("precision",
                         ["bf16x3", "bf16x3f", "int8", "int4", "pq",
                          "highest"])
@pytest.mark.parametrize("kernel", ["tiled", "streaming"])
def test_db_byte_terms_match_actual_operand_nbytes(rng, precision, kernel):
    """Property: the model's per-pass db byte terms equal the nbytes of
    the arrays the kernel really streams, for both db-streaming
    strategies across the f32/bf16/int8 operand families."""
    n, d = 512, 128
    db = jnp.asarray(rng.random((n, d), dtype=np.float32) * 128)
    values_b, aux_b = _actual_operand_nbytes(db, precision)
    model_b = roofline.db_operand_nbytes(n, d, precision)
    assert model_b["db_values"] == values_b
    assert model_b["db_aux"] == aux_b
    # and the full model's hbm term is exactly passes x those bytes
    m = roofline.pallas_cost_model(
        n=n, d=d, k=5, nq=64, precision=precision, kernel=kernel,
        tile_n=128, block_q=32, device_kind="TPU v5e")
    passes = m["terms"]["hbm"]["db_passes"]
    assert passes == -(-64 // 32)  # query-major: one pass per block
    assert m["terms"]["hbm"]["bytes"]["db_stream"] == passes * values_b
    assert m["terms"]["hbm"]["bytes"]["db_aux"] == passes * aux_b


def test_geometry_defaults_mirror_kernel_constants():
    """The jax-free module mirrors the kernel's geometry defaults; a
    drift here would silently mis-model every default-knob config."""
    from knn_tpu.ops import pallas_knn as pk

    assert roofline.TILE_N_DEFAULT == pk.TILE_N
    assert roofline.BLOCK_Q_DEFAULT == pk.BLOCK_Q
    assert roofline.BIN_W == pk.BIN_W
    assert roofline.DIM_CHUNK == pk.DIM_CHUNK
    # the fused-arm disarm threshold the overlapped-ceiling call mirrors
    assert roofline.MAX_CARRY_DEPTH == pk.MAX_CARRY_DEPTH
    n_bins, surv, out_w, bound_w = pk._geometry(pk.TILE_N)
    assert surv == roofline.SURVIVORS_GROUPED_DEFAULT
    # grouped default survivors=2 -> the out/bound widths the candidate
    # output term assumes
    assert out_w == surv * pk.BIN_W and bound_w == pk.BIN_W


def test_bench_peak_table_is_a_view_over_roofline():
    import bench

    assert bench._PEAK_BY_KIND == roofline.bf16_peak_by_kind()
    assert bench._PEAK_BY_KIND["TPU v5 lite"] == 197e12


# --- MODEL_VERSION 6: the sub-int8 compressed tiers ---------------------


def test_sub_int8_row_bytes_pinned():
    """Pinned byte ratios at SIFT dims (docs/PERF.md precision
    ladder): int4 streams HALF int8's row (an eighth of f32), pq at
    the default dsub=4 streams m = ceil(d/4) code bytes — m/(4d) of
    the f32 row, 1/16 at d=128."""
    from knn_tpu.analysis import widths

    f32 = widths.db_row_bytes(128, "highest")
    i8 = widths.db_row_bytes(128, "int8")
    i4 = widths.db_row_bytes(128, "int4")
    pq = widths.db_row_bytes(128, "pq", dsub=4)
    assert (f32, i8, i4, pq) == (512, 128, 64, 32)
    assert i4 / i8 == 0.5 and i4 / f32 == 0.125
    assert pq / f32 == widths.pq_nsub(128, 4) / (4 * 128) == 1 / 16
    # int4's packed aux (norms row 0 + scales row 1 in ONE 8-row
    # block) also halves int8's 16-row broadcast block
    a = roofline.db_operand_nbytes(1000, 128, "int4")
    b = roofline.db_operand_nbytes(1000, 128, "int8")
    assert 2 * a["db_aux"] == b["db_aux"]


def test_int4_streaming_breaks_the_int8_hbm_ceiling():
    """THE acceptance pin of the compressed-tier ISSUE: at the
    hbm-bound operating point (small nq, block_q=8, SIFT1M on a v5e)
    both int8 and int4 streaming hit the HBM wall, and halving the
    streamed bytes lifts the modeled ceiling >= 1.8x."""
    assert roofline.MODEL_VERSION == 7
    kw = dict(n=1_000_000, d=128, k=10, nq=8, kernel="streaming",
              block_q=8, device_kind="TPU v5e", backend="tpu")
    m8 = roofline.pallas_cost_model(precision="int8", **kw)
    m4 = roofline.pallas_cost_model(precision="int4", **kw)
    assert m8["bound_class"] == "hbm_bound"
    assert m4["bound_class"] == "hbm_bound"
    assert m4["ceiling_qps"] >= 1.8 * m8["ceiling_qps"]
    assert roofline.validate_block(m4) == []


def test_pq_model_prices_lut_width_and_composes_with_probes():
    """pq's full-db stream is NOT a free lunch: the one-hot LUT
    contraction prices at m*ncodes MXU width, so the full stream is
    mxu_bound; composed with IVF probing (MODEL_VERSION 5 knobs) the
    byte and flop reductions multiply and the ceiling climbs."""
    base = dict(n=1_000_000, d=128, k=10, nq=8, kernel="streaming",
                block_q=8, device_kind="TPU v5e", backend="tpu")
    full = roofline.pallas_cost_model(precision="pq", **base)
    assert full["bound_class"] == "mxu_bound"
    probed = roofline.pallas_cost_model(precision="pq", nprobe=32,
                                        ncentroids=1024, **base)
    assert probed["ceiling_qps"] > full["ceiling_qps"]
    assert roofline.validate_block(probed) == []


# --- ceilings bound measured reality -----------------------------------


def test_interpret_mode_run_sits_under_the_cpu_ceiling(rng):
    """roofline_pct <= 1 + tolerance against a real (interpret-mode,
    CPU) run: even against the deliberately modest generic-CPU fallback
    peaks, an interpreted kernel can never beat its own roofline."""
    import time

    from knn_tpu.ops.pallas_knn import knn_search_pallas

    n, d, k, nq = 2048, 64, 5, 16
    db = rng.random((n, d), dtype=np.float32) * 128
    q = rng.random((nq, d), dtype=np.float32) * 128
    import jax

    d_, i_, _ = knn_search_pallas(q, db, k, tile_n=512)  # compile/warm
    jax.block_until_ready((d_, i_))
    t0 = time.perf_counter()
    out = knn_search_pallas(q, db, k, tile_n=512)
    jax.block_until_ready(out[:2])
    qps = nq / (time.perf_counter() - t0)
    model = roofline.pallas_cost_model(
        n=n, d=d, k=k, nq=nq, tile_n=512, backend="cpu")
    assert model["estimated"] is True
    att = roofline.attribute(model, qps)
    assert att["roofline_pct"] is not None
    assert att["roofline_pct"] <= 1.05


def test_r05_sift1m_curated_line_is_hbm_bound():
    """Pinned regression: the r05 SIFT1M curated line (bf16x3, tiled,
    query_major on a v5e) attributes its MFU gap to the db-streaming
    term — hbm_bound, at a small measured fraction of the ceiling.
    This is THE named gap ROADMAP item 1's kernel campaign attacks."""
    path = os.path.join(REPO, "TPU_BENCH_r05.jsonl")
    rec = None
    for line in open(path):
        cand = json.loads(line)
        if cand.get("metric", "").startswith("knn_qps_sift1m"):
            rec = cand
            break
    assert rec is not None, "r05 SIFT1M curated line missing"
    block = roofline.block_for_bench_line(rec)
    assert block is not None
    assert block["estimated"] is False
    assert block["bound_class"] == "hbm_bound"
    # measured 24.2k device-phase q/s against a ~184k ceiling
    assert 0.05 < block["roofline_pct"] < 0.3
    assert roofline.validate_block(block) == []


def test_bound_class_moves_with_the_config():
    """The model names a different gap per campaign lever (the whole
    point of attribution): int8 x streaming leaves the select as the
    wall, db_major at single-chunk dims removes the streaming term,
    and the XLA exact path is selection-bound."""
    base = dict(n=1_000_000, d=128, k=100, nq=4096,
                device_kind="TPU v5 lite", backend="tpu")
    assert roofline.pallas_cost_model(**base)["bound_class"] == "hbm_bound"
    m8 = roofline.pallas_cost_model(
        precision="int8", kernel="streaming", **base)
    assert m8["bound_class"] == "vpu_select_bound"
    assert m8["ceiling_qps"] > roofline.pallas_cost_model(
        **base)["ceiling_qps"]
    mdb = roofline.pallas_cost_model(grid_order="db_major", **base)
    assert mdb["bound_class"] == "mxu_bound"
    assert mdb["terms"]["hbm"]["db_passes"] == 1
    mx = roofline.xla_cost_model(
        selector="exact", dtype="bfloat16", batch=512, **base)
    assert mx["bound_class"] == "vpu_select_bound"
    # approx runs two db passes — its hbm/mxu terms double
    ma = roofline.xla_cost_model(
        selector="approx", dtype="bfloat16", batch=512, **base)
    assert ma["terms"]["mxu"]["flops_executed"] == \
        2 * mx["terms"]["mxu"]["flops_executed"]


def test_cpu_fallback_peaks_flag_estimated():
    m = roofline.pallas_cost_model(
        n=10_000, d=32, k=5, nq=64, device_kind="TPU v99", backend="tpu")
    assert m["estimated"] is True  # unknown kind -> generic fallback
    m2 = roofline.pallas_cost_model(
        n=10_000, d=32, k=5, nq=64, device_kind="TPU v5e", backend="cpu")
    assert m2["estimated"] is True  # cpu backend beats a known kind
    line = {"metric": "knn_qps_x_n10000_d32_k5", "mode": "exact",
            "value": 100.0, "backend": "cpu", "compute_dtype": "float32",
            "batch": 32}
    block = roofline.block_for_bench_line(line)
    assert block["estimated"] is True
    assert block["roofline_pct"] is not None


# --- validation --------------------------------------------------------


def test_validate_block_accepts_real_and_rejects_malformed():
    good = roofline.attribute(
        roofline.pallas_cost_model(n=1000, d=16, k=5, nq=8), 50.0)
    assert roofline.validate_block(good) == []
    assert roofline.validate_block("nope")  # not a dict
    assert roofline.validate_block({})  # everything missing
    bad = dict(good, bound_class="gpu_bound")
    assert any("bound_class" in e for e in roofline.validate_block(bad))
    bad = dict(good, ceiling_qps=-3)
    assert any("ceiling_qps" in e for e in roofline.validate_block(bad))
    bad = dict(good, terms={"hbm": {"time_s": -1}})
    assert roofline.validate_block(bad)


# --- tuning cache integration ------------------------------------------


def test_cache_key_carries_roofline_token_and_pre_roofline_misses(
        tmp_path):
    """Satellite: the cache-key version bump — entries written before
    the roofline fields existed (no |rl token) must miss and fall back
    to defaults cleanly; current-token entries hit and surface their
    persisted attribution through resolve_full."""
    cache_path = str(tmp_path / "tune.json")
    key = tuning.cache_key("cpu", 700, 16, 5, "l2", None)
    assert f"|rl{roofline.MODEL_VERSION}|" in key
    # a pre-roofline entry: same shape, no rl token (the old format)
    pre = key.replace(f"|rl{roofline.MODEL_VERSION}", "")
    cache = tuning.TuneCache(cache_path)
    cache.put(pre, {"knobs": {**tuning.DEFAULT_KNOBS,
                              "kernel": "streaming"}})
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "default"
    assert knobs == tuning.DEFAULT_KNOBS
    # a STALE-token entry (the MODEL_VERSION 5 key, before the
    # compressed-tier arms re-priced the grid) must miss the same way:
    # the version bump self-invalidates every pre-6 winner
    stale = key.replace(f"|rl{roofline.MODEL_VERSION}|", "|rl5|")
    assert stale != key
    cache.put(stale, {"knobs": {**tuning.DEFAULT_KNOBS,
                                "kernel": "streaming"}})
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path)
    assert info["source"] == "default"
    # a current entry carrying the winner's attribution DOES hit, and
    # the verdict rides the resolve info + the /statusz store
    block = roofline.attribute(
        roofline.pallas_cost_model(n=700, d=16, k=5, nq=64), 500.0)
    cache.put(key, {"knobs": dict(tuning.DEFAULT_KNOBS),
                    "roofline_pct": block["roofline_pct"],
                    "bound_class": block["bound_class"],
                    "roofline": block})
    knobs, info = tuning.resolve_full(700, 16, 5, cache_path=cache_path,
                                      device_kind="cpu")
    assert info["source"] == "cache"
    assert info["roofline_pct"] == block["roofline_pct"]
    assert info["bound_class"] == block["bound_class"]
    reports = roofline.last_reports()
    label = roofline.config_label(700, 16, 5, device_kind="cpu")
    assert label in reports
    assert reports[label]["bound_class"] == block["bound_class"]


def test_autotune_persists_winner_attribution(rng, tmp_path):
    """The autotuner reports percent-of-roofline per candidate and
    persists the winner's verdict in the cache entry."""
    cache_path = str(tmp_path / "tune.json")
    db = rng.random((768, 16), np.float32) * 128
    q = rng.random((8, 16), np.float32) * 128
    entry = tuning.autotune(db, q, 5, grid_level="quick", runs=1,
                            cache_path=cache_path)
    assert entry["bound_class"] in roofline.BOUND_CLASSES
    assert 0 < entry["roofline_pct"] <= 1.05
    assert roofline.validate_block(entry["roofline"]) == []
    # every TIMED candidate got an attribution
    timed = [lbl for lbl, ms in entry["timings_ms"].items()
             if ms is not None]
    for lbl in timed:
        cand = entry["roofline_per_candidate"][lbl]
        assert cand["bound_class"] in roofline.BOUND_CLASSES
        assert cand["roofline_pct"] > 0
    # the persisted entry round-trips the fields on a warm read
    warm = tuning.autotune(db, q, 5, grid_level="quick", runs=1,
                           cache_path=cache_path)
    assert warm["cached"] is True
    assert warm["roofline_pct"] == entry["roofline_pct"]


# --- registry / statusz / obs-off --------------------------------------


def test_publish_exports_metrics_and_statusz_renders():
    block = roofline.attribute(
        roofline.pallas_cost_model(n=1000, d=16, k=5, nq=8,
                                   device_kind="TPU v5e",
                                   backend="tpu"), 100.0)
    roofline.publish("TPU v5e|n1000|d16|k5|l2|float32", block)
    snap = obs.snapshot()
    series = snap[mn.ROOFLINE_PCT]["series"]
    assert series[0]["labels"]["config"] == \
        "TPU v5e|n1000|d16|k5|l2|float32"
    assert series[0]["value"] == block["roofline_pct"]
    bounds = {(s["labels"]["class"], s["value"])
              for s in snap[mn.ROOFLINE_BOUND]["series"]}
    assert (block["bound_class"], 1.0) in bounds
    assert obs.counter(mn.ROOFLINE_EVALUATIONS).get() == 1.0
    text = obs.prometheus_text()
    assert "knn_tpu_roofline_ceiling_qps" in text
    rep = health.report()
    assert "TPU v5e|n1000|d16|k5|l2|float32" in rep["roofline"]
    rendered = health.render_text(rep)
    assert "roofline TPU v5e|n1000|d16|k5|l2|float32" in rendered
    assert block["bound_class"] in rendered


def test_publish_is_a_noop_when_obs_disabled():
    obs.reset(enabled=False)
    try:
        block = roofline.attribute(
            roofline.pallas_cost_model(n=1000, d=16, k=5, nq=8), 10.0)
        roofline.publish("cpu|n1000|d16|k5|l2|float32", block)
        assert roofline.last_reports() == {}
        assert "knn_tpu_roofline" not in obs.prometheus_text()
    finally:
        obs.reset()


def test_last_reports_store_is_bounded():
    block = roofline.attribute(
        roofline.pallas_cost_model(n=1000, d=16, k=5, nq=8), 10.0)
    for i in range(roofline._LAST_MAX + 4):
        roofline.publish(f"cpu|n{i}|d16|k5|l2|float32", block)
    assert len(roofline.last_reports()) == roofline._LAST_MAX
    # the publish-once dedup survives the bounded store's eviction —
    # otherwise a warm-cache hot path serving many configs would
    # re-publish (and re-emit events) on every resolve
    assert "cpu|n0|d16|k5|l2|float32" not in roofline.last_reports()
    assert roofline.was_published("cpu|n0|d16|k5|l2|float32")


def test_lint_skips_advisory_error_blocks_but_fails_malformed(tmp_path):
    """scripts/perf_sentinel.py --lint: bench's advisory
    {"error": ...} degradation blocks are a designed outcome (never a
    CI failure); a structurally malformed block IS one."""
    import subprocess
    import sys

    script = os.path.join(REPO, "scripts", "perf_sentinel.py")

    def lint(lines):
        (tmp_path / "TPU_BENCH_r01.jsonl").write_text(
            "".join(json.dumps(r) + "\n" for r in lines))
        return subprocess.run(
            [sys.executable, script, "--lint", "--repo", str(tmp_path)],
            capture_output=True, text=True, timeout=120)

    good = roofline.attribute(
        roofline.pallas_cost_model(n=1000, d=16, k=5, nq=8), 10.0)
    base = {"metric": "knn_qps_x_n1000_d16_k5", "value": 10.0,
            "backend": "tpu", "measured_round": 1,
            "measured_at_commit": "abc"}
    r = lint([dict(base, roofline=good),
              dict(base, roofline={"error": "ValueError: model gap"})])
    assert r.returncode == 0, r.stderr
    assert "1 validated, 1 advisory-error blocks skipped" in r.stdout
    r = lint([dict(base, roofline={"bound_class": "gpu_bound"})])
    assert r.returncode == 1
    assert "roofline block" in r.stderr


# --- sentinel integration ----------------------------------------------


def test_sentinel_judges_roofline_pct_as_a_curated_field():
    """The sentinel's roofline_pct family: read off the top level or
    out of the line's roofline block, judged like any curated field —
    regressions are measured against the model's ceiling, not only
    against raw-qps history."""
    hist = []
    for i, pct in enumerate((0.13, 0.131, 0.129, 0.132)):
        hist.append({
            "metric": "knn_qps_sift1m_n1000000_d128_k100",
            "value": 6000.0 + i, "backend": "tpu",
            "measured_round": i + 1, "measured_at_commit": f"c{i}",
            # half hoisted, half block-only: both must enter
            **({"roofline_pct": pct} if i % 2 else
               {"roofline": {"roofline_pct": pct}}),
        })
    base = sentinel.build_baselines(hist)
    key = "knn_qps_sift1m_n1000000_d128_k100|tpu|default"
    assert "roofline_pct" in base[key]
    fresh = {"metric": "knn_qps_sift1m_n1000000_d128_k100",
             "backend": "tpu", "value": 6001.0,
             "roofline": {"roofline_pct": 0.06}}
    v = sentinel.verdict_for_line(fresh, baselines=base)
    assert v["fields"]["roofline_pct"]["verdict"] == "regress"
    fresh["roofline"]["roofline_pct"] = 0.13
    v = sentinel.verdict_for_line(fresh, baselines=base)
    assert v["fields"]["roofline_pct"]["verdict"] == "ok"


# --- profiler ----------------------------------------------------------


def test_profiler_gates(tmp_path, monkeypatch):
    from knn_tpu.obs import profiler

    # no env, no flag -> no capture, not even a directory
    monkeypatch.delenv(profiler.PROFILE_ENV, raising=False)
    with profiler.device_trace("sect") as tdir:
        assert tdir is None
    # env gate honors the obs switch
    monkeypatch.setenv(profiler.PROFILE_ENV, str(tmp_path / "amb"))
    obs.reset(enabled=False)
    try:
        assert profiler.profile_dir() is None
        # ... but an explicit flag is an explicit request either way
        with profiler.device_trace("m|ode x",
                                   base_dir=str(tmp_path / "exp")) as td:
            assert td == str(tmp_path / "exp" / "m_ode_x")
            jnp.square(jnp.arange(4.0)).block_until_ready()
        assert os.path.isdir(td)
    finally:
        obs.reset()
    # obs back on: the env gate opens
    with profiler.device_trace("tune") as td:
        assert td == str(tmp_path / "amb" / "tune")
        jnp.square(jnp.arange(4.0)).block_until_ready()
    assert os.path.isdir(td)
    events = [e for e in obs.get_event_log().recent()
              if e.get("name") == "profiler.trace"]
    assert events and events[-1]["trace_dir"] == td


# --- cli ---------------------------------------------------------------


def test_cli_roofline_subcommand(capsys):
    from knn_tpu import cli

    rc = cli.main(["roofline", "--n", "1000000", "--dim", "128",
                   "--k", "100", "--device-kind", "TPU v5 lite",
                   "--qps", "24199.3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "hbm_bound" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["bound_class"] == "hbm_bound"
    # MODEL_VERSION 2: the non-fused select serializes after the stream
    # (max(hbm, mxu) + vpu), so the default-knob SIFT ceiling is ~118k
    # and the r05 24.2k device phase reads ~21% of roofline
    assert tail["roofline_pct"] == pytest.approx(0.206, abs=0.01)
    rc = cli.main(["roofline", "--n", "100000", "--dim", "960",
                   "--k", "10", "--selector", "approx",
                   "--dtype", "bfloat16", "--batch", "512", "--json"])
    assert rc == 0
    block = json.loads(capsys.readouterr().out)
    assert roofline.validate_block(block) == []


# --- MODEL_VERSION 4: the multi-host DCN merge term ---------------------

def test_dcn_term_only_on_multihost_blocks():
    base = dict(n=1_000_000, d=128, k=10, nq=4096,
                device_kind="TPU v5e", backend="tpu", num_devices=8)
    single = roofline.pallas_cost_model(precision="int8", **base)
    multi = roofline.pallas_cost_model(precision="int8", db_hosts=4,
                                       dcn_merge="ring", **base)
    assert "dcn" not in single["terms"]
    dcn = multi["terms"]["dcn"]
    from knn_tpu.parallel.crossover import merge_bytes

    assert dcn["bytes"] == merge_bytes(4096, 10, 4, "ring")
    assert dcn["hosts"] == 4 and dcn["strategy"] == "ring"
    # the DCN merge serializes after compute: ceiling strictly drops
    assert multi["ceiling_qps"] < single["ceiling_qps"]
    # recompute the combined-time formula from the block's own terms
    # (tiled kernel: select serialized, then the DCN merge after it)
    t = multi["term_times_s"]
    assert multi["select_overlapped"] is False
    expect = 4096 / (max(t["hbm_bound"], t["mxu_bound"])
                     + t["vpu_select_bound"] + t["dcn_bound"])
    assert multi["ceiling_qps"] == pytest.approx(expect, rel=1e-3)
    assert roofline.validate_block(multi) == []


def test_dcn_bound_class_and_strategy_default():
    # a pathologically slow DCN makes the merge the binding resource
    peaks = dict(roofline.PEAKS_BY_KIND["TPU v5e"], dcn_gbps=1e-6)
    m = roofline.xla_cost_model(n=100_000, d=64, k=100, nq=2048,
                                selector="exact", db_hosts=8,
                                peaks=peaks)
    assert m["bound_class"] == "dcn_bound"
    # dcn_merge=None resolves through the measured crossover table
    from knn_tpu.parallel.crossover import choose_merge

    assert m["terms"]["dcn"]["strategy"] == choose_merge(100, 8)
    # multihost blocks carry an explicitly-absent calibration verdict
    assert m["calibration"]["applied"] is False
    assert "dcn" in roofline.render_text(m)


def test_validate_block_rejects_malformed_dcn_term():
    m = roofline.pallas_cost_model(
        n=1_000_000, d=128, k=10, nq=4096, precision="int8",
        device_kind="TPU v5e", backend="tpu", db_hosts=2)
    assert roofline.validate_block(m) == []
    bad = {**m, "terms": {**m["terms"],
                          "dcn": {**m["terms"]["dcn"], "hosts": 1,
                                  "strategy": "bogus"}}}
    errs = roofline.validate_block(bad)
    assert any("terms.dcn.hosts" in e for e in errs)
    assert any("terms.dcn.strategy" in e for e in errs)
