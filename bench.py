#!/usr/bin/env python
"""Benchmark: brute-force KNN queries/sec at SIFT1M shape (1M x 128, k=100 —
BASELINE.json config 3) on whatever devices JAX exposes (the driver runs this
on one real TPU chip).

Prints EXACTLY ONE JSON line:
  {"metric": ..., "value": <q/s>, "unit": "queries/s", "vs_baseline": <x>, ...}
On any failure (including backend init) it still prints one JSON line, with
an "error" field, so the driver always gets a parseable record.

Three measured configurations (the ``selectors`` table in the JSON):

- ``exact``           coarse top-(K+margin) via lax.top_k + float64 host
                      refinement — the selection-bound baseline path.
- ``certified_approx``  the flagship: hardware ApproxTopK coarse pass +
                      float64 refine + count-below certificate + exact
                      fallback (ops.certified).  Exact by construction.
- ``certified_pallas``  same pipeline with the fused Pallas distance+bin-min
                      kernel (ops.pallas_knn) as the coarse pass.

``value`` is the best configuration whose recall@K against the float64 CPU
oracle is 1.0.  Protocol follows the reference report (PDF p.12 §4.2):
each configuration is timed KNN_BENCH_RUNS (default 5) times after a
warmup sweep; mean/std/min are reported.  MFU relates measured q/s to the
matmul FLOPs actually executed (2*N*D per query per database pass) against
the chip's peak — the "fast, not merely correct" check.  Beside it, every
selector entry (and the line top-level, for the winner) carries a
``roofline`` block (knn_tpu.obs.roofline): the analytic per-config ceiling
q/s from the HBM/MXU/VPU cost model, the measured ``roofline_pct``, and
the ``bound_class`` naming the resource to attack — attribution, where
MFU alone is only a ratio.

``vs_baseline`` divides by the reference-style CPU brute force: the native
C++ backend (knn_tpu/native, the reference program's semantics with
std::thread standing in for its MPI ranks) timed on a query subsample of
the SAME database.  The reference's own published numbers are MNIST-shaped
and machine-specific (BASELINE.md); an in-situ CPU measurement is the
honest denominator.

Env overrides:
  KNN_BENCH_CONFIG   sift1m (default) | glove | gist1m   (BASELINE configs 3/4/5)
  KNN_BENCH_MODES    comma list from {exact,certified_approx,
                     certified_pallas,serving,knee,multihost,mutation,
                     ivf,join,quality,fleet}; ``join`` is the opt-in
                     bulk kNN-join line (knn_tpu.join: double-buffered
                     superblock stream vs looped serving on the same
                     placement; KNN_BENCH_JOIN_ROWS/_SUPERBLOCK/_DEPTH
                     shape it); ``quality`` is the opt-in shadow-audit
                     replay (knn_tpu.obs.audit at rate 1.0:
                     KNN_BENCH_QUALITY_REQUESTS requests re-scored
                     against the f64 exact oracle); ``fleet`` is the
                     opt-in cross-host telemetry merge
                     (knn_tpu.obs.fleet over KNN_TPU_FLEET_MEMBERS, or
                     this process's own snapshot as a one-member
                     fleet)
  KNN_BENCH_RUNS     timed repetitions per mode (default 5)
  KNN_BENCH_N, KNN_BENCH_DIM, KNN_BENCH_K, KNN_BENCH_NQ, KNN_BENCH_BATCH,
  KNN_BENCH_TILE, KNN_BENCH_CPU_QUERIES, KNN_BENCH_MARGIN,
  KNN_BENCH_DTYPE    (bfloat16 | float32; default per config)
  KNN_BENCH_PEAK_FLOPS    override the per-chip peak used for MFU
  KNN_BENCH_PLATFORM      force a JAX platform (e.g. "cpu") before init
  KNN_BENCH_TRACE         write a jax.profiler trace of one extra per-mode
                          run under this directory (TensorBoard-viewable;
                          the --trace-dir flag is equivalent; the ambient
                          KNN_TPU_PROFILE_DIR gate of knn_tpu.obs.profiler
                          also opens this capture when telemetry is on)
  KNN_BENCH_PALLAS_KERNEL tiled | streaming (db-streaming strategy);
                          unset pallas knobs resolve through the
                          knn_tpu.tuning winner cache (see
                          KNN_BENCH_TUNE_CACHE / `knn_tpu.cli tune`)
  KNN_BENCH_INIT_TIMEOUT  seconds before backend init is declared hung (480)
  KNN_BENCH_FALLBACK_CPU  run on CPU if accelerator init fails — DEFAULT ON
                          (the JSON records backend+device so the number
                          stays honest; a flagged CPU number beats a null
                          round record — BENCH_r03).  Set 0 to disable.
"""

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np


def _parse_args(argv=None):
    """The bench's (tiny) flag surface — unknown args are ignored so the
    driver's bare ``python bench.py`` invocation stays untouched."""
    p = argparse.ArgumentParser(
        prog="bench.py",
        description="KNN throughput bench; prints exactly one JSON line",
    )
    p.add_argument(
        "--trace-dir", default=os.environ.get("KNN_BENCH_TRACE"),
        metavar="DIR",
        help="capture a jax.profiler trace artifact (utils.timing.trace, "
        "TensorBoard-loadable) of one extra per-mode run under DIR, "
        "alongside the bench JSON; equivalent to KNN_BENCH_TRACE",
    )
    args, _ = p.parse_known_args(argv)
    return args


ARGS = _parse_args()


def _env_int(name, default):
    return int(os.environ.get(name, default))


def _env_opt_int(name):
    return int(os.environ[name]) if name in os.environ else None


#: BASELINE.json configs 3/4/5.  ``certifiable`` = the certificate
#: machinery applies: l2 natively, cosine via the library's unit-vector
#: l2 equivalence (ShardedKNN normalizes rows at placement).  L1 would
#: not be (no squared-L2-style bound).
CONFIGS = {
    "sift1m": dict(n=1_000_000, dim=128, k=100, metric="l2", dtype="bfloat16"),
    "glove": dict(n=1_183_514, dim=300, k=50, metric="cosine", dtype="bfloat16"),
    "gist1m": dict(n=1_000_000, dim=960, k=100, metric="l2", dtype="bfloat16"),
}

try:
    CONFIG = os.environ.get("KNN_BENCH_CONFIG", "sift1m")
    _cfg = CONFIGS[CONFIG]
    N = _env_int("KNN_BENCH_N", _cfg["n"])
    DIM = _env_int("KNN_BENCH_DIM", _cfg["dim"])
    K = _env_int("KNN_BENCH_K", _cfg["k"])
    METRIC = os.environ.get("KNN_BENCH_METRIC", _cfg["metric"])
    NQ = _env_int("KNN_BENCH_NQ", 4096)
    BATCH = _env_int("KNN_BENCH_BATCH", 512)  # sweep winner on v5e (2026-07)
    TILE = _env_int("KNN_BENCH_TILE", 131_072)
    #: 256 queries (VERDICT r2 item 7): ~40 s of CPU once per round buys a
    #: 4x larger denominator sample; cpu_queries + per-query time stay in
    #: the JSON so the claim is auditable.
    CPU_QUERIES = _env_int("KNN_BENCH_CPU_QUERIES", 256)
    #: pallas kernel knob OVERRIDES.  Unset env = None = resolve through
    #: knn_tpu.tuning (the persisted autotuner winner for this exact
    #: (device_kind, n, d, k, metric, dtype) when one exists, else the
    #: library defaults); a SET env var always wins over both — the same
    #: precedence ShardedKNN.search_certified applies, so the bench and
    #: the library can never run different knobs for the same request.
    PALLAS_PRECISION = os.environ.get("KNN_BENCH_PALLAS_PRECISION")
    PALLAS_TILE = _env_opt_int("KNN_BENCH_PALLAS_TILE")
    PALLAS_BIN_W = _env_opt_int("KNN_BENCH_PALLAS_BIN_W")
    PALLAS_SURVIVORS = _env_opt_int("KNN_BENCH_PALLAS_SURVIVORS")
    PALLAS_BLOCK_Q = _env_opt_int("KNN_BENCH_PALLAS_BLOCK_Q")
    PALLAS_FINAL = os.environ.get("KNN_BENCH_PALLAS_FINAL")
    #: select-phase layout (ops.pallas_knn.BINNINGS): "grouped" = lane-
    #: indexed bins, shuffle-free select (round-4); "lane" = round-3
    PALLAS_BINNING = os.environ.get("KNN_BENCH_PALLAS_BINNING")
    #: grid iteration order (ops.pallas_knn.GRID_ORDERS): "db_major"
    #: streams each db tile once per sweep instead of once per query
    #: block (r5 cost model); opt-in pending the hardware gate + A/B
    PALLAS_GRID = os.environ.get("KNN_BENCH_PALLAS_GRID")
    #: db-streaming strategy (ops.pallas_knn.KERNELS): "tiled" | the
    #: one-launch double-buffered "streaming"
    PALLAS_KERNEL = os.environ.get("KNN_BENCH_PALLAS_KERNEL")
    #: autotuner cache file override (KNN_TPU_TUNE_CACHE also works)
    TUNE_CACHE = os.environ.get("KNN_BENCH_TUNE_CACHE")
    #: recall target of the one-pass path's final ApproxTopK (None =
    #: library default 0.999); misses surface as fallbacks, never
    #: as unsound certificates
    PALLAS_FINAL_RT = (float(os.environ["KNN_BENCH_PALLAS_FINAL_RT"])
                       if "KNN_BENCH_PALLAS_FINAL_RT" in os.environ else None)
    #: pallas sweep batch size (0/unset = one full-size batch); smaller
    #: batches pipeline the d2h transfer under later batches' compute
    PALLAS_BATCH = _env_int("KNN_BENCH_PALLAS_BATCH", 0) or None
    #: certified_approx calibration (TUNING_r03: rt=0.9999 zeroed the
    #: genuine ApproxTopK misses; the adaptive gap threshold handles
    #: the rest, and the wider margin feeds its gap search)
    APPROX_RT = float(os.environ.get("KNN_BENCH_APPROX_RT", "0.9999"))
    APPROX_MARGIN = _env_int("KNN_BENCH_APPROX_MARGIN", 128)
    DTYPE = os.environ.get("KNN_BENCH_DTYPE", _cfg["dtype"])
    RUNS = _env_int("KNN_BENCH_RUNS", 5)
    #: Coarse pass fetches K + MARGIN candidates; float64 refinement
    #: re-selects the true top-K among them (ops.refine); the certificate
    #: (ops.certified) then proves no true neighbor was missed, or falls back.
    MARGIN = _env_int("KNN_BENCH_MARGIN", 28)
    #: ``serving`` mode trace: request count, in-flight dispatch-ahead
    #: window, and the bucket ladder's floor (ladder tops out at BATCH)
    SERVING_REQUESTS = _env_int("KNN_BENCH_SERVING_REQUESTS", 48)
    SERVING_DEPTH = _env_int("KNN_BENCH_SERVING_DEPTH", 2)
    SERVING_MIN_BUCKET = _env_opt_int("KNN_BENCH_SERVING_MIN_BUCKET")
    #: measure telemetry overhead (knn_tpu.obs): replay the serving
    #: trace twice — registry disabled, then enabled — and report
    #: obs_overhead_pct = (qps_off - qps_on) / qps_off * 100.  Opt-in:
    #: the double replay costs a second trace of chip time.
    OBS_OVERHEAD = os.environ.get("KNN_BENCH_OBS_OVERHEAD", "0") == "1"
    #: ``knee`` mode (knn_tpu.loadgen): open-loop stepped-rate sweep
    #: through the micro-batching queue, locating the
    #: latency-vs-throughput knee.  Opt-in via KNN_BENCH_MODES=..,knee
    #: (each rate step costs KNEE_STEP_S wall seconds).  Unset
    #: KNEE_RATES = a ladder of KNEE_FRACTIONS x a measured closed-loop
    #: anchor rate.
    KNEE_RATES = [float(x) for x in os.environ.get(
        "KNN_BENCH_KNEE_RATES", "").split(",") if x.strip()]
    KNEE_STEP_S = float(os.environ.get("KNN_BENCH_KNEE_STEP_S", "1.0"))
    KNEE_SLO_MS = float(os.environ.get("KNN_BENCH_KNEE_SLO_MS", "100"))
    KNEE_TENANTS = os.environ.get("KNN_BENCH_KNEE_TENANTS", "default:1")
    KNEE_SEED = _env_int("KNN_BENCH_KNEE_SEED", 0)

    #: multi-host serving measurement (hierarchical merge + host-RAM
    #: tier).  Opt-in via KNN_BENCH_MODES=..,multihost
    MULTIHOST_HOSTS = _env_int("KNN_BENCH_MULTIHOST_HOSTS", 2)
    MULTIHOST_SWEEPS = _env_int("KNN_BENCH_MULTIHOST_SWEEPS", 4)

    #: ``mutation`` mode (knn_tpu.index + knn_tpu.loadgen): live mixed
    #: read+write traffic against a MutableIndex-backed serving stack
    #: across background compaction swaps.  Opt-in via
    #: KNN_BENCH_MODES=..,mutation (docs/INDEX.md)
    MUTATION_RATE = float(os.environ.get(
        "KNN_BENCH_MUTATION_RATE", "200"))
    MUTATION_SECONDS = float(os.environ.get(
        "KNN_BENCH_MUTATION_SECONDS", "2.0"))
    MUTATION_WRITE_FRACTION = float(os.environ.get(
        "KNN_BENCH_MUTATION_WRITE_FRACTION", "0.15"))

    #: ``join`` mode (knn_tpu.join): offline bulk kNN-join of a
    #: host-resident query set against the placed corpus through the
    #: double-buffered superblock stream, beside a looped-serving
    #: baseline on the SAME placement — the amortization claim as one
    #: line.  Opt-in via KNN_BENCH_MODES=..,join.  JOIN_ROWS=0 sizes
    #: the query set from NQ/BATCH; JOIN_SUPERBLOCK=0 defers to the
    #: engine's resolution ladder (KNN_TPU_JOIN_* applies there too).
    JOIN_ROWS = _env_int("KNN_BENCH_JOIN_ROWS", 0)
    JOIN_SUPERBLOCK = _env_int("KNN_BENCH_JOIN_SUPERBLOCK", 0)
    JOIN_DEPTH = _env_int("KNN_BENCH_JOIN_DEPTH", 2)

    #: ``quality`` mode (knn_tpu.obs.audit): a short serving replay
    #: with the shadow audit sampler forced to rate 1.0, so EVERY
    #: request's served top-k is re-scored against the f64 exact
    #: oracle on the audit worker thread.  Opt-in via
    #: KNN_BENCH_MODES=..,quality; each request pays one host-side
    #: oracle scan over the full corpus, so the count stays small.
    QUALITY_REQUESTS = _env_int("KNN_BENCH_QUALITY_REQUESTS", 8)
except Exception as _e:  # bad env: the one-JSON-line contract still holds
    print(json.dumps({
        "metric": "knn_qps_config", "value": None, "unit": "queries/s",
        "vs_baseline": None, "error": f"config: {_e!r}",
    }))
    sys.exit(1)

#: bf16 MXU peak FLOP/s by device kind — a VIEW over the roofline
#: module's full peak table (knn_tpu.obs.roofline.PEAKS_BY_KIND, the
#: single source of truth, which additionally carries HBM GB/s and the
#: int8 MXU / VPU rates the per-config cost model divides by).  MFU is
#: an *estimate* — the denominator assumes bf16 peak even for f32 runs.
#: An unknown kind yields mfu=null WITH an explicit mfu_reason (below),
#: never a silently-wrong default; the guarded import keeps the
#: one-JSON-line contract even if the package is broken.
def _load_peak_by_kind():
    try:
        from knn_tpu.obs.roofline import bf16_peak_by_kind

        return bf16_peak_by_kind()
    except Exception:  # noqa: BLE001 — an empty table = mfu_reason, not a crash
        return {}


_PEAK_BY_KIND = _load_peak_by_kind()


_GIT_COMMIT_MEMO = [False]  # False = not probed yet (None = no repo)


def _git_commit():
    """Short git HEAD stamped into every emitted line, so a session
    measurement carries its own code provenance into curation
    (scripts/refresh_bench_artifacts.py's measured_at_commit).  Probed
    once per process — _emit may run several times (error paths)."""
    if _GIT_COMMIT_MEMO[0] is not False:
        return _GIT_COMMIT_MEMO[0]
    import subprocess

    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        _GIT_COMMIT_MEMO[0] = r.stdout.strip() or None
    except Exception:
        _GIT_COMMIT_MEMO[0] = None
    return _GIT_COMMIT_MEMO[0]


def _emit(obj):
    commit = _git_commit()
    if commit and "measured_at_commit" not in obj:
        obj = {**obj, "measured_at_commit": commit}
    print(json.dumps(obj))
    sys.stdout.flush()


def _vlog(msg):
    """Stage progress on stderr when KNN_BENCH_VERBOSE=1 — the bench's
    stdout carries exactly one JSON line, so diagnosing a hang (stale
    device claim, slow remote compile) needs a side channel."""
    if os.environ.get("KNN_BENCH_VERBOSE") == "1":
        print(f"[bench +{time.monotonic() - _T0:.0f}s] {msg}",
              file=sys.stderr, flush=True)


_T0 = time.monotonic()


def _fail(stage, err, **extra):
    _emit({
        "metric": f"knn_qps_{CONFIG}_n{N}_d{DIM}_k{K}",
        "value": None,
        "unit": "queries/s",
        "vs_baseline": None,
        "error": f"{stage}: {err}",
        **extra,
    })
    sys.exit(1)


def _relay_ports_refused():
    """True when this environment's accelerator relay is definitively
    absent: the axon client dials 127.0.0.1:8083 (stateless device
    enumeration) / :8082 (session) when AXON_POOL_SVC_OVERRIDE pins the
    pool service to loopback, and a refused TCP connect there means no
    tunnel exists — the client would otherwise spin its connect-retry
    loop for ~25 minutes before surfacing UNAVAILABLE (measured during
    the round-4 relay outage).  Only consulted for that specific
    override, so generic environments keep the full probe."""
    if os.environ.get("AXON_POOL_SVC_OVERRIDE") != "127.0.0.1":
        return False
    import socket

    for port in (8083, 8082):
        s = socket.socket()
        s.settimeout(2.0)
        try:
            s.connect(("127.0.0.1", port))
        except ConnectionRefusedError:
            continue
        except OSError:
            return False  # filtered/timeout: can't conclude absence
        else:
            return False  # something listens: relay may be alive
        finally:
            s.close()
    return True


def _probe_backend_subprocess(timeout):
    """Attempt backend init in a KILLABLE child process.  Returns
    (ok, err, hung): ok=True means a child saw jax.devices() succeed
    moments ago, so an in-process init is near-certain to succeed too;
    hung=True means the child was SIGKILLed at the timeout (a stale
    device claim) — the caller's wait policy escalates on it.  A hung
    child is SIGKILLed and the parent's backend-init lock stays clean —
    the round-3 failure mode (a hung make_c_api_client inside this
    process held the lock, so neither retry nor CPU fallback could ever
    run; BENCH_r03.json shipped null)."""
    import subprocess

    env = dict(os.environ)
    plat = os.environ.get("KNN_BENCH_PLATFORM")
    # the platform force happens IN the child via jax.config.update —
    # env vars lose to sitecustomize plugins (same reason as the
    # in-process path below)
    force = f"jax.config.update('jax_platforms', {plat!r}); " if plat else ""
    code = (
        f"import jax, sys; {force}d = jax.devices(); "
        "print('OK', d[0].platform, len(d)); sys.stdout.flush()"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], env=env, timeout=timeout,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
    except subprocess.TimeoutExpired:
        # subprocess.run kills the child on timeout before raising
        return False, f"probe hung > {timeout}s (stale device claim?)", True
    # match any line, not a prefix: the sitecustomize plugins this
    # harness injects may write to stdout before the probe's own print
    lines = r.stdout.strip().splitlines()
    if r.returncode == 0 and any(ln.startswith("OK") for ln in lines):
        return True, None, False
    tail = (r.stderr or r.stdout).strip().splitlines()
    return False, f"probe rc={r.returncode}: {tail[-1] if tail else '?'}", False


def _init_backend():
    """Initialize the JAX backend, surviving flaky accelerator attach.

    Strategy (VERDICT r3 item 1a): each init attempt runs first in a
    SUBPROCESS probe with a kill-on-timeout watchdog, with exponentially
    growing waits between attempts (a stale device claim expires with
    time; one in-process 480 s wait was not enough in round 3).  Only
    after a probe succeeds does this process import jax and init — by
    then the claim is known live, so the in-process watchdog below is a
    belt-and-braces backstop, not the primary defense.  If every probe
    fails, the parent has never touched the accelerator init path, so
    the CPU fallback is always clean to take."""
    import threading

    if "jax" in sys.modules:
        # in-process callers (scripts/archive/tpu_session.py) arrive with the
        # backend already initialized and HOLDING the device claim — a
        # subprocess probe would deadlock against our own claim, so
        # short-circuit when a backend is already live
        try:
            import jax
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                return jax
        except Exception:  # pragma: no cover - private API moved
            pass

    timeout = _env_int("KNN_BENCH_INIT_TIMEOUT", 480)
    attempts = _env_int("KNN_BENCH_INIT_ATTEMPTS", 3)
    wait0 = _env_int("KNN_BENCH_INIT_WAIT", 60)

    probe_err = None
    probe_ok = False
    for attempt in range(attempts):
        if not os.environ.get("KNN_BENCH_PLATFORM") and _relay_ports_refused():
            # no tunnel at all: don't burn the probe timeout spinning the
            # client's 25-minute connect-retry loop — fail this attempt
            # fast so the CPU fallback can land a line within any driver
            # budget.  A quick re-check each attempt still catches a
            # tunnel that comes up mid-loop.
            probe_ok, probe_err, hung = (
                False, "relay ports 8082/8083 refused (no tunnel)", False)
            _vlog(f"backend probe {attempt + 1}/{attempts}: {probe_err}")
            if attempt + 1 < attempts:
                time.sleep(5.0)
            continue
        _vlog(f"backend probe {attempt + 1}/{attempts} "
              f"(timeout {timeout}s) ...")
        probe_ok, probe_err, hung = _probe_backend_subprocess(timeout)
        if probe_ok:
            break
        _vlog(f"probe failed: {probe_err}")
        if attempt + 1 < attempts:
            # only a HUNG probe earns the long exponential wait (a stale
            # claim drains with time); a fast rc!=0 failure (no
            # accelerator at all) retries quickly so the CPU fallback
            # isn't delayed by minutes
            wait = wait0 * (2 ** attempt) if hung else 5.0
            _vlog(f"waiting {wait}s before the next probe ...")
            time.sleep(wait)
    def cpu_fallback(err):
        """jax on the CPU backend, or _fail with the accumulated error.
        Safe from both call sites: on the probe-failure path the parent
        never attempted accelerator init, and on the post-probe path
        every init attempt RAISED (a hang _fails before reaching here),
        so the backend-init lock is free either way."""
        if os.environ.get("KNN_BENCH_FALLBACK_CPU", "1") != "0":
            try:
                import jax

                jax.config.update("jax_platforms", "cpu")
                jax.devices()
                return jax
            except Exception as e:  # noqa: BLE001
                err = f"{err}; cpu fallback failed: {e!r}"
        _fail("backend_init", err)

    if not probe_ok:
        return cpu_fallback(probe_err)

    # probe green: in-process init with a watchdog as backstop.  The
    # probe child HELD the claim moments ago and its release can lag, so
    # transient "device busy" raises here get bounded retries; a raised
    # (non-hung) failure can still fall back to CPU — only a hang forfeits
    # both (the hung thread owns the backend-init lock forever).
    state = {}

    def work():
        try:
            import jax

            plat = os.environ.get("KNN_BENCH_PLATFORM")
            if plat:  # in-process force (env vars lose to sitecustomize plugins)
                jax.config.update("jax_platforms", plat)
            state["devices"] = jax.devices()
            state["jax"] = jax
        except Exception as e:  # noqa: BLE001 — recorded and retried
            state["error"] = repr(e)

    last_err = "unknown"
    for attempt in range(attempts):
        state.pop("error", None)
        t = threading.Thread(target=work, daemon=True)
        t.start()
        t.join(timeout)
        if "devices" in state:
            return state["jax"]
        if t.is_alive():
            _fail("backend_init",
                  f"in-process init hung > {timeout}s AFTER a green "
                  f"subprocess probe (claim went stale in the gap)")
        last_err = state.get("error", "unknown")
        _vlog(f"in-process init failed: {last_err}")
        if attempt + 1 < attempts:
            time.sleep(min(10.0 * (attempt + 1), 30.0))
            try:  # drop the cached failed backend so the retry re-attaches
                import jax

                jax.clear_caches()
                from jax._src import xla_bridge

                xla_bridge.backends.cache_clear()
            except Exception:  # pragma: no cover - cache API moved
                pass
    return cpu_fallback(last_err)


def recall_at_k(pred_idx: np.ndarray, true_idx: np.ndarray) -> float:
    hits = 0
    for p, t in zip(pred_idx, true_idx):
        hits += len(set(p.tolist()) & set(t.tolist()))
    return hits / true_idx.size


#: the baseline is deterministic (fixed-seed data, same binary, same
#: machine), and at gist shape it costs ~12 min — cache it on disk so a
#: device-holding bench run doesn't re-burn that time.  The JSON marks
#: reused measurements with cpu_baseline_cached so the claim stays
#: auditable; KNN_BENCH_CPU_CACHE=0 forces a fresh measurement.
_CPU_CACHE_USED = False


def _cpu_baseline(db, sub):
    """Native C++ brute force (reference semantics) on the subsample:
    (qps, mean per-query seconds, exact f64 top-K indices)."""
    global _CPU_CACHE_USED
    cache = None
    if os.environ.get("KNN_BENCH_CPU_CACHE", "1") != "0":
        cache = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f".bench_cpu_{CONFIG}_{METRIC}_n{N}_d{DIM}_k{K}_q{len(sub)}.npz",
        )
        if os.path.exists(cache):
            try:
                z = np.load(cache)
                _CPU_CACHE_USED = True
                return float(z["qps"]), float(z["per_q"]), z["idx"]
            except Exception:
                pass
    try:
        from knn_tpu import native

        if not native.available():
            return None, None, None
        t0 = time.perf_counter()
        _, idx = native.knn_search(db, sub, K, METRIC, num_threads=8)
        elapsed = time.perf_counter() - t0
        qps, per_q = len(sub) / elapsed, elapsed / len(sub)
        if cache:
            try:
                np.savez(cache, qps=qps, per_q=per_q, idx=idx)
            except Exception:
                pass
        return qps, per_q, idx
    except Exception:
        return None, None, None


def main() -> None:
    _vlog("init backend ...")
    jax = _init_backend()
    dev = jax.devices()[0]
    backend = jax.default_backend()

    global N, NQ, RUNS, CPU_QUERIES
    cpu_shrunk = False
    if backend == "cpu" and os.environ.get("KNN_BENCH_PLATFORM") != "cpu":
        # CPU FALLBACK auto-shrink (an explicitly requested
        # KNN_BENCH_PLATFORM=cpu run is honored at full size): the FULL
        # sift1m sweep needs ~3 TFLOP per timed run — hours on this
        # host's single core, so a driver timeout would turn the
        # fallback line into nothing at all (the exact regression the
        # fallback exists to prevent).  Explicit env overrides are
        # respected; the shrink is visible in the metric name (n/dim/k
        # are embedded) and flagged below.
        def cap(env_key, value, limit):
            nonlocal cpu_shrunk
            if env_key in os.environ or value <= limit:
                return value
            cpu_shrunk = True
            return limit

        N = cap("KNN_BENCH_N", N, 100_000)
        NQ = cap("KNN_BENCH_NQ", NQ, 512)
        RUNS = cap("KNN_BENCH_RUNS", RUNS, 2)
        CPU_QUERIES = cap("KNN_BENCH_CPU_QUERIES", CPU_QUERIES, 32)
        if cpu_shrunk:
            _vlog(f"cpu backend: shrunk to N={N} NQ={NQ} RUNS={RUNS}")

    def curated_tpu_reference():
        """When this run is a CPU FALLBACK (relay down at bench time),
        point the emitted line at the round's curated TPU measurement
        for the same config — the fallback line then carries the real
        hardware evidence (clearly labeled as a pointer, not a
        measurement of this run) instead of only a shrunken CPU number.
        Reads the newest TPU_BENCH_r*.jsonl next to this script."""
        import glob

        here = os.path.dirname(os.path.abspath(__file__))
        files = sorted(glob.glob(os.path.join(here, "TPU_BENCH_r*.jsonl")))
        if not files:
            return None
        want_prefix = f"knn_qps_{CONFIG}_"
        try:
            lines = open(files[-1]).read().splitlines()
        except OSError:
            return None
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # skip the one bad/blank line, not the lookup
            if (str(rec.get("metric", "")).startswith(want_prefix)
                    # only a REAL hardware line may stand in as TPU
                    # evidence — a curated file can itself contain a
                    # CPU-fallback record for a config
                    and rec.get("backend") == "tpu"
                    and not rec.get("cpu_fallback_shrunk")):
                return {
                    "source": os.path.basename(files[-1]),
                    "metric": rec.get("metric"),
                    "value": rec.get("value"),
                    "device_phase_qps": rec.get("device_phase_qps"),
                    "pallas_gate_ok": rec.get("pallas_gate_ok"),
                    "recall_at_k": rec.get("recall_at_k"),
                    "backend": rec.get("backend"),
                }
        return None
    # peak FLOPs for MFU: env override > known device kind > None (a v5e
    # default on an unknown/CPU backend would yield a meaningless MFU).
    # When peak is unknowable, mfu_reason says WHY the line's mfu fields
    # are null — "cpu backend" (no MXU peak to relate to) vs "unknown
    # device kind" (extend the table / set KNN_BENCH_PEAK_FLOPS) — so
    # sentinel baselines can key on MFU exactly where it exists
    mfu_reason = None
    if "KNN_BENCH_PEAK_FLOPS" in os.environ:
        peak = float(os.environ["KNN_BENCH_PEAK_FLOPS"])
    else:
        peak = _PEAK_BY_KIND.get(getattr(dev, "device_kind", ""))
        if peak is None:
            mfu_reason = (
                "cpu backend: no MXU peak to relate measured FLOPs to"
                if backend == "cpu" else
                f"unknown device kind "
                f"{getattr(dev, 'device_kind', str(dev))!r}: not in "
                f"_PEAK_BY_KIND and KNN_BENCH_PEAK_FLOPS unset")

    from knn_tpu.ops.refine import refine_exact
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN

    rng = np.random.default_rng(0)
    db = (rng.random(size=(N, DIM)) * 128.0).astype(np.float32)
    queries = (rng.random(size=(NQ, DIM)) * 128.0).astype(np.float32)
    sub = queries[:CPU_QUERIES]

    _vlog(f"data generated ({N}x{DIM}); CPU baseline on {CPU_QUERIES} queries ...")
    cpu_qps, cpu_per_q_s, oracle_idx = _cpu_baseline(db, sub)
    _vlog(f"cpu baseline done: {cpu_qps and round(cpu_qps, 2)} q/s")

    metric_label = METRIC
    if METRIC == "cosine":
        # the library handles cosine natively now: ShardedKNN normalizes
        # the db rows at placement and search_certified runs the whole
        # certified-exact machinery on unit vectors (the round-3 harness
        # did this normalization trick itself; VERDICT r3 item 4 moved it
        # into the library).  The CPU oracle above ranked true cosine on
        # the raw data, so the recall check validates the equivalence
        # end-to-end.
        metric_label = "cosine (certified via unit-vector l2)"

    global DTYPE
    if oracle_idx is None and "KNN_BENCH_DTYPE" not in os.environ:
        # no oracle to verify bf16 recall against -> stay conservative for
        # the exact (margin-heuristic) path; certified modes re-verify
        # themselves either way
        DTYPE = "float32"

    mesh = make_mesh()  # all devices; (1,1) on a single chip
    tile = min(TILE, N)
    coarse_k = min(K + MARGIN, N)
    certifiable = METRIC in ("l2", "sql2", "euclidean", "cosine")

    # Default sweep: certified_approx stays OFF the accelerator loop — it
    # decided nothing in two rounds of hardware data (1,071 q/s vs exact's
    # 2,168, TPU_BENCH_r04.jsonl) and tunnel minutes are the scarcest
    # resource; it remains fully covered on CPU (tests + this default) and
    # reachable anywhere via KNN_BENCH_MODES.
    # ``serving`` rides along by default: it reuses the placement and its
    # trace is tiny next to the timed sweeps, but it is the only line that
    # measures the variable-batch-size traffic pattern (sustained q/s +
    # tail latency through the bucketed engine)
    if not certifiable:
        default_modes = "exact,serving"
    elif backend == "cpu":
        default_modes = "exact,certified_approx,certified_pallas,serving"
    else:
        default_modes = "exact,certified_pallas,serving"
    modes = os.environ.get("KNN_BENCH_MODES", default_modes).split(",")

    # ONE device placement of the (padded) database, shared by every mode:
    # the exact path fetches k+margin via search(k=...), the certified
    # paths use their own cached programs on the same placement.
    def build(dtype):
        return ShardedKNN(db, mesh=mesh, k=K, metric=METRIC,
                          train_tile=tile, compute_dtype=dtype)

    _vlog("placing database on device ...")
    prog = build(DTYPE)
    if DTYPE == "bfloat16" and oracle_idx is not None:
        # recall-gate the dtype before committing to the full measurement:
        # bf16 matmuls that misrank past the margin can't be repaired on
        # the non-certified path, so demote to float32 (certified modes
        # self-repair either way, but the headline must stay exact)
        _vlog("bf16 recall gate ...")
        _, ci = prog.search(sub, k=coarse_k)
        _, ri = refine_exact(db, sub, np.asarray(ci), K, METRIC)
        if recall_at_k(ri, oracle_idx) < 1.0:
            DTYPE = "float32"
            del prog  # free the bf16 placement before the rebuild
            prog = build(DTYPE)

    # resolve the pallas knobs ONCE (after the dtype demotion so the key
    # matches the placement search_certified will see): env overrides >
    # persisted autotuner winner (`python -m knn_tpu.cli tune`) > library
    # defaults.  One exception preserved from two rounds of measurement:
    # on a cache MISS with no env pin, the bench keeps its historical
    # "approx" final (the measured relay-side winner, TUNING_r03)
    # instead of the library's "exact" default; a cache HIT carries a
    # MEASURED final_select (tuning.knob_grid searches it at every
    # level), so the winner rightly takes precedence then.
    from knn_tpu import tuning

    KNOBS, TUNE_INFO = tuning.resolve_full(
        N, DIM, K, metric="l2" if METRIC == "cosine" else METRIC,
        dtype=DTYPE, cache_path=TUNE_CACHE,
        overrides=dict(
            tile_n=PALLAS_TILE, block_q=PALLAS_BLOCK_Q, bin_w=PALLAS_BIN_W,
            survivors=PALLAS_SURVIVORS, precision=PALLAS_PRECISION,
            final_select=PALLAS_FINAL, binning=PALLAS_BINNING,
            grid_order=PALLAS_GRID, final_recall_target=PALLAS_FINAL_RT,
            kernel=PALLAS_KERNEL,
        ),
    )
    if TUNE_INFO["source"] == "default" and "final_select" not in \
            TUNE_INFO["overridden"]:
        KNOBS["final_select"] = "approx"
    _vlog(f"pallas knobs ({TUNE_INFO['source']}): {KNOBS}")

    def batches(qs):
        for lo in range(0, qs.shape[0], BATCH):
            chunk = qs[lo : lo + BATCH]
            pad = BATCH - chunk.shape[0]
            yield lo, np.pad(chunk, ((0, pad), (0, 0))) if pad else chunk, pad

    def sweep_exact(qs):
        """Coarse device top-(K+margin), f64 host refine overlapped with the
        next batches' device work.  Returns (idx [Q,K], stats=None)."""
        coarse = [(lo, prog.search(chunk, k=coarse_k), pad)
                  for lo, chunk, pad in batches(qs)]
        out = []
        for lo, (d, i), pad in coarse:
            i = np.asarray(i)
            if pad:
                i = i[:-pad]
            out.append(refine_exact(db, qs[lo : lo + i.shape[0]], i, K, METRIC)[1])
        return np.concatenate(out), None

    def sweep_certified(selector, return_distances=True):
        def run(qs):
            if selector == "pallas":
                # ONE device pass; PALLAS_BATCH pipelines the d2h
                # transfer of batch b under the device compute of the
                # batches behind it (None = one big batch+transfer).
                # The resolved KNOBS pass as explicit values, so the
                # library-side resolve is a no-op re-statement of them.
                _, i, st = prog.search_certified(
                    qs, margin=MARGIN, selector=selector,
                    batch_size=PALLAS_BATCH,
                    return_distances=return_distances,
                    **KNOBS,
                )
                return i, st
            # counted path: all coarse selects dispatch up front, host
            # refine overlaps later batches' device work (sharded.py)
            _, i, st = prog.search_certified(
                qs, margin=APPROX_MARGIN if selector == "approx" else MARGIN,
                selector=selector, batch_size=BATCH,
                recall_target=APPROX_RT,
            )
            return i, st
        return run

    def sweep_serving():
        """Variable-batch-size trace through the shape-bucketed serving
        engine (knn_tpu.serving): log-uniform request sizes in [1, BATCH]
        replayed with a bounded dispatch-ahead window.  Reports SUSTAINED
        q/s and p50/p95/p99 request latency — the traffic-pattern number
        the single-shot sweeps above cannot measure — plus the compile
        accounting that proves the bucket ladder bounded the XLA compile
        count."""
        from knn_tpu.serving.engine import ServingEngine

        min_bucket = SERVING_MIN_BUCKET or max(1, BATCH // 32)
        eng = ServingEngine(prog, min_bucket=min_bucket, max_bucket=BATCH)
        t0 = time.perf_counter()
        eng.warmup()
        warm_s = time.perf_counter() - t0
        t_rng = np.random.default_rng(42)
        sizes = np.exp(
            t_rng.uniform(0.0, np.log(BATCH), size=SERVING_REQUESTS)
        ).astype(np.int64).clip(1, BATCH)
        reqs = []
        for s in sizes:
            lo = int(t_rng.integers(0, max(1, NQ - int(s))))
            reqs.append(queries[lo : lo + int(s)])
        _, report = eng.replay(reqs, depth=SERVING_DEPTH)
        obs_overhead = None
        if OBS_OVERHEAD:
            # A/B the SAME trace with telemetry off, then on — fresh
            # engines so neither run inherits the other's counters;
            # warmup() keeps compiles out of both replay windows.  The
            # ambient registry state is restored afterwards (env-driven).
            from knn_tpu import obs as _obs

            qps = {}
            for on in (False, True):
                _obs.reset(enabled=on)
                e2 = ServingEngine(
                    prog, min_bucket=min_bucket, max_bucket=BATCH)
                e2.warmup()
                # one untimed replay first: each arm's executables pay
                # their first-execution costs OUTSIDE the timed window,
                # or the off-first ordering reads as phantom overhead;
                # then best-of-3 per arm — replay jitter dwarfs the
                # per-event cost, so the comparison needs the noise
                # floor pushed down, not one sample
                e2.replay(reqs, depth=SERVING_DEPTH)
                best = None
                for _ in range(3):
                    _, rep2 = e2.replay(reqs, depth=SERVING_DEPTH)
                    if rep2["sustained_qps"] is not None:
                        best = max(best or 0.0, rep2["sustained_qps"])
                qps[on] = best
            _obs.reset()
            if qps[False] and qps[True]:
                obs_overhead = round(
                    (qps[False] - qps[True]) / qps[False] * 100.0, 3)
        # worst recent requests' trace ids (histogram exemplars via
        # engine stats): the replay's tail percentiles become
        # joinable against spans/waterfalls (cli waterfall) when an
        # obs log or postmortem bundle was captured alongside
        slowest_ids = [e.get("trace_id")
                       for e in report.get("slowest_requests") or []
                       if e.get("trace_id")][:5]
        return {
            "sustained_qps": report["sustained_qps"],
            "latency_ms": report["latency_ms"],
            **({"slowest_trace_ids": slowest_ids} if slowest_ids else {}),
            # telemetry overhead on this trace (None = not measured; set
            # KNN_BENCH_OBS_OVERHEAD=1): negative values are replay
            # noise — the honest reading is "below noise floor"
            **({"obs_overhead_pct": obs_overhead}
               if obs_overhead is not None else {}),
            "trace_requests": report["requests"],
            "trace_queries": report["total_queries"],
            "trace_wall_s": report["wall_s"],
            "dispatch_depth": SERVING_DEPTH,
            "warmup_s": round(warm_s, 4),
            "bucket_ladder": report["buckets"],
            "compile_count": report["compile_count"],
            "executables": report["executables"],
            "per_bucket_dispatches": report["per_bucket_dispatches"],
            "donate_queries": report["donate_queries"],
            # which kernel knobs a certified path on this placement
            # would resolve (persisted winner vs defaults)
            "tuning": report.get("tuning"),
        }

    def sweep_knee():
        """Open-loop stepped-rate sweep (knn_tpu.loadgen) through the
        micro-batching queue: the latency-vs-throughput knee as a
        curated artifact (rate steps, admitted p50/p95/p99, shed
        fraction, detected knee q/s).  Admission control participates
        when the KNN_TPU_ADMISSION_* env knobs are set — the brownout
        configuration — and stays off otherwise, measuring the raw
        engine.  Request rates are REQUESTS/s (mixed batch sizes, like
        real traffic), anchored on a short closed-loop probe when
        KNN_BENCH_KNEE_RATES is unset."""
        from knn_tpu import loadgen
        from knn_tpu.serving.admission import AdmissionConfig
        from knn_tpu.serving.engine import ServingEngine
        from knn_tpu.serving.queue import QueryQueue

        min_bucket = SERVING_MIN_BUCKET or max(1, BATCH // 32)
        eng = ServingEngine(prog, min_bucket=min_bucket, max_bucket=BATCH)
        eng.warmup()
        admission = AdmissionConfig.from_env()
        tenants = tuple(
            loadgen.TenantSpec(t.name, weight=t.weight,
                               priority=t.priority,
                               batch_sizes=(1, 2, 4, 8))
            for t in loadgen.parse_tenants(KNEE_TENANTS))
        base = loadgen.WorkloadSpec(
            rate_qps=1.0, duration_s=KNEE_STEP_S, seed=KNEE_SEED,
            tenants=tenants)
        rates = KNEE_RATES
        anchor = None
        if not rates:
            # closed-loop anchor probe (admission-free queue): the
            # default ladder brackets the knee around it
            with QueryQueue(eng, max_wait_ms=2.0) as q0:
                anchor = loadgen.closed_loop_anchor(q0, queries)
            rates = loadgen.rates_around(anchor)

        def make_queue():
            return QueryQueue(eng, max_wait_ms=2.0, admission=admission)

        block = loadgen.knee_sweep(
            make_queue, base, rates, queries=queries,
            slo_p99_ms=KNEE_SLO_MS)
        return {
            "loadgen_knee": block,
            "knee_qps": block["knee_qps"],
            "slo_p99_ms": KNEE_SLO_MS,
            "anchor_req_qps": (round(anchor, 2)
                               if anchor is not None else None),
            "admission_enabled": admission is not None,
            "rates": [float(r) for r in rates],
            "tenants": KNEE_TENANTS,
        }

    def sweep_mutation():
        """Opt-in mixed read+write traffic proof (knn_tpu.index): a
        MutableIndex-backed serving stack (bucketed engine + delta
        tail + micro-batching queue) driven by a seeded open-loop
        schedule whose tenants carry a write stream, with background
        compaction thresholds sized so the run crosses >= 2 snapshot
        swaps.  Emits the validated ``mutation`` artifact block
        (knn_tpu.index.artifact) — admitted-read p99 beside write
        counts, compactions, and SLO breach transitions."""
        from knn_tpu import loadgen, obs
        from knn_tpu.index.mutable import MutableIndex
        from knn_tpu.obs import names as _mn
        from knn_tpu.serving.queue import QueryQueue

        # cap the index's own placement: the mutation line measures
        # swap behavior under traffic, not raw scan throughput (the
        # timed modes own that), and compaction re-places the corpus
        # once per swap
        n_idx = min(N, 131072)
        mix_frac = max(0.0, min(1.0, MUTATION_WRITE_FRACTION))
        insert_frac = round(mix_frac * 2 / 3, 4)
        delete_frac = round(mix_frac / 3, 4)
        expected_inserts = MUTATION_RATE * MUTATION_SECONDS * insert_frac
        idx = MutableIndex(
            db[:n_idx], mesh=mesh, k=K, metric="l2",
            train_tile=tile,
            # ~2 threshold crossings over the run, floor of 8 so tiny
            # smoke runs still swap at least once
            compact_tail_rows=max(8, int(expected_inserts / 2.5) or 8))
        eng = idx.serving_engine(
            min_bucket=SERVING_MIN_BUCKET or max(1, BATCH // 32),
            max_bucket=BATCH)
        eng.warmup()
        idx.start_compactor()
        # one write-only tenant at weight = the requested mix: overall
        # write share == mix_frac for any fraction in (0, 1)
        tenants = (
            loadgen.TenantSpec("readers", weight=1.0 - mix_frac,
                               batch_sizes=(1, 2, 4, 8)),
            loadgen.TenantSpec("writers", weight=mix_frac,
                               batch_sizes=(1,),
                               insert_fraction=round(2 / 3, 4),
                               delete_fraction=round(1 / 3, 4)),
        ) if mix_frac else (
            loadgen.TenantSpec("readers", batch_sizes=(1, 2, 4, 8)),)
        spec = loadgen.WorkloadSpec(
            rate_qps=MUTATION_RATE, duration_s=MUTATION_SECONDS,
            seed=KNEE_SEED, tenants=tenants)
        def _breach_total():
            if not obs.enabled():
                return 0
            return sum(s["value"] for s in obs.snapshot().get(
                _mn.SLO_BREACH_TRANSITIONS, {}).get("series", []))

        breach0 = _breach_total()
        try:
            with QueryQueue(eng, max_wait_ms=2.0) as q:
                rep = loadgen.run_workload(
                    q, loadgen.generate(spec), queries=queries)
        finally:
            idx.close()
        breach1 = _breach_total()
        st = idx.stats()
        lat = rep.get("latency_ms") or {}
        swap_hist = (obs.histogram(_mn.INDEX_SWAP_SECONDS).summary()
                     if obs.enabled() else None) or {}
        block = {
            "mutation_version": 1,
            "write_mix": {"insert_fraction": insert_frac,
                          "delete_fraction": delete_frac},
            "rate_qps": MUTATION_RATE,
            "duration_s": MUTATION_SECONDS,
            "index_rows": n_idx,
            "admitted_p99_ms": lat.get("p99"),
            "admitted_p50_ms": lat.get("p50"),
            "achieved_qps": rep.get("achieved_qps"),
            "compactions": int(st["compactions"]),
            "epoch": int(st["epoch"]),
            "swap_seconds_max": swap_hist.get("max"),
            "reads": {"offered": rep["offered"], "ok": rep["ok"],
                      "rejected": rep["rejected"],
                      "shed": rep["shed"], "errors": rep["errors"]},
            "writes": dict(rep.get("writes") or {}),
            "slo_breach_transitions": int(breach1 - breach0),
        }
        from knn_tpu.index.artifact import validate_mutation_block

        errs = validate_mutation_block(block)
        if errs:
            block["validation_errors"] = errs
        return {"mutation": block,
                "mutation_admitted_p99_ms": lat.get("p99")}

    def sweep_ivf():
        """Opt-in IVF tier measurement (knn_tpu.ivf): train the
        list-major placement, run the certified probed search over the
        full query set, and emit the validated ``ivf`` artifact block —
        recall_at_k / probe_fraction / fallback_rate /
        bytes_streamed_ratio beside the probed qps.  Every run also
        re-asserts the exactness anchor on a sub-batch: the
        nprobe=ncentroids arm must reproduce exact brute force bitwise,
        or the block carries the mismatch as its error instead of a
        lying rate.  ncentroids/nprobe come from the KNN_TPU_IVF_*
        switch family (index defaults: round(sqrt(n)), ncentroids/4)."""
        from knn_tpu.ivf import IVFIndex
        from knn_tpu.ivf.artifact import IVF_VERSION, validate_ivf_block
        from knn_tpu.ops.refine import refine_shared_exact

        # cap the trained placement like mutation mode: this line
        # measures the pruning tradeoff, not raw scan throughput
        n_idx = min(N, 131072)
        idx = IVFIndex(db[:n_idx], mesh=mesh, k=K, metric="l2",
                       train_tile=tile)
        ist = idx.stats()
        idx.search_certified(queries[:BATCH])  # warm/compile off-clock
        times = []
        stats = None
        for _ in range(RUNS):
            t0 = time.perf_counter()
            _, _, stats = idx.search_certified(queries)
            times.append(time.perf_counter() - t0)
        qps = round(NQ / float(np.mean(times)), 2)
        anchor_err = None
        try:
            aq = queries[: min(BATCH, 256)]
            d_all, i_all, _ = idx.search_certified(
                aq, nprobe=ist["ncentroids"])
            d_ref, i_ref = refine_shared_exact(
                db[:n_idx], aq, np.arange(n_idx, dtype=np.int64), K)
            if not (np.array_equal(i_all, i_ref)
                    and np.array_equal(d_all, d_ref)):
                anchor_err = ("exactness anchor: nprobe=ncentroids "
                              "!= brute force bitwise")
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            anchor_err = f"exactness anchor: {type(e).__name__}: {e}"
        block = {
            "ivf_version": IVF_VERSION,
            "ncentroids": int(stats["ncentroids"]),
            "nprobe": int(stats["nprobe"]),
            "queries": int(stats["queries"]),
            "k": int(stats["k"]),
            "probe_fraction": stats["probe_fraction"],
            "recall_at_k": stats["recall_at_k"],
            "fallback_rate": stats["fallback_rate"],
            "bytes_streamed_ratio": stats["bytes_streamed_ratio"],
            "qps": qps,
            "selector": stats["selector"],
            "fallback_queries": int(stats["fallback_queries"]),
            "certified_queries": int(stats["certified_queries"]),
            "genuine_misses": int(stats["genuine_misses"]),
            "epoch": int(ist["epoch"]),
            "compactions": int(ist["compactions"]),
        }
        if anchor_err:
            block["error"] = anchor_err
        errs = validate_ivf_block(block)
        if errs:
            block["validation_errors"] = errs
        return {"ivf": block}

    def sweep_multihost():
        """Multi-host serving measurement, two arms on one line:

        (a) the HIERARCHICAL placement — a make_host_mesh fold of the
        available devices into (query, host, chip), per-chip candidates
        reduced per-host over the ICI db axis then globally over the
        host axis at the crossover-resolved strategies — timed against
        the flat-mesh placement's own numbers elsewhere on the line
        (results are bitwise-identical; tests pin that, the bench
        measures the merge-tree overhead);

        (b) the HOST-RAM shard tier — the same corpus forced through a
        budget sized for ~KNN_BENCH_MULTIHOST_SWEEPS sweeps, streaming
        segment-by-segment with dispatch-ahead — per-sweep walls show
        whether the stream held flat.

        The entry's roofline block models the cluster: ``db_hosts``
        hosts and the MODEL_VERSION-4 DCN merge term, validated by the
        artifact refresher like every roofline block."""
        from knn_tpu.analysis import hbm as _hbm
        from knn_tpu.obs import roofline as _rl
        from knn_tpu.parallel import crossover as _xover
        from knn_tpu.parallel.mesh import make_host_mesh

        hosts = MULTIHOST_HOSTS
        ndev = len(jax.devices())
        if ndev % hosts:
            raise RuntimeError(
                f"{ndev} devices not divisible by "
                f"KNN_BENCH_MULTIHOST_HOSTS={hosts}")
        per_host = ndev // hosts
        chips = 2 if per_host % 2 == 0 else 1
        qs = per_host // chips
        mesh_h = make_host_mesh(qs, hosts, chips)
        prog_h = ShardedKNN(db, mesh=mesh_h, k=K, metric=METRIC,
                            train_tile=tile)
        nq_run = min(NQ, BATCH)
        qb = queries[:nq_run]
        np.asarray(prog_h.search(qb)[0])  # warm, BLOCKED (async dispatch)
        times = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            d, _ = prog_h.search(qb)
            np.asarray(d)
            times.append(time.perf_counter() - t0)
        qps_h = nq_run / float(np.mean(times))

        # host-RAM tier: budget sized so the corpus takes ~MULTIHOST_SWEEPS
        # sweeps (per-host share), streamed through the flat mesh
        rows_padded = -(-N // (len(mesh.devices.ravel()))) * len(
            mesh.devices.ravel())
        total_b = _hbm.placement_bytes(rows_padded, DIM)
        budget = max(1, -(-total_b // (hosts * MULTIHOST_SWEEPS)))
        # the budget is derived from a byte model that rounds differently
        # than ShardedKNN's own accounting; halve until the tier really
        # engages so the arm can never silently measure a resident
        # placement as a "stream"
        prog_t, ht = None, None
        for _ in range(4):
            prog_t = ShardedKNN(db, mesh=mesh_h, k=K, metric=METRIC,
                                train_tile=tile, hbm_budget_bytes=budget)
            ht = prog_t.hosttier_stats()
            if ht is not None:
                break
            budget = max(1, budget // 2)
        if ht is None:
            raise RuntimeError(
                f"host-RAM tier never engaged down to budget={budget} B "
                f"for n={N}, d={DIM}; shrink KNN_BENCH_MULTIHOST_SWEEPS")
        np.asarray(prog_t.search(qb)[0])  # warm, blocked
        t0 = time.perf_counter()
        d, _ = prog_t.search(qb)
        np.asarray(d)
        tier_wall = time.perf_counter() - t0
        ht = prog_t.hosttier_stats()
        last = ht.get("last_search") or {}

        block = {
            "hosts": hosts,
            "chips_per_host": chips,
            "merge": {
                "intra": {"strategy": prog_h.merge,
                          "source": prog_h.merge_source},
                "dcn": {"strategy": prog_h.dcn_merge,
                        "source": prog_h.dcn_merge_source},
            },
            "dcn_merge_bytes": _xover.merge_bytes(
                nq_run, K, hosts, prog_h.dcn_merge),
            "hosttier": {
                "sweeps": int(last.get("sweeps") or ht["sweeps"]),
                "budget_bytes": int(ht["budget_bytes"]),
                "segment_rows": int(ht["segment_rows"]),
                "bytes_per_sweep": int(ht["bytes_per_sweep"]),
                "sweep_walls_s": last.get("sweep_walls_s"),
                "qps": round(nq_run / tier_wall, 2),
            },
        }
        model = _rl.xla_cost_model(
            n=N, d=DIM, k=K, nq=nq_run, selector="exact",
            dtype="float32", batch=nq_run,
            device_kind=getattr(dev, "device_kind", ""), backend=backend,
            num_devices=ndev, db_hosts=hosts,
            dcn_merge=prog_h.dcn_merge)
        return {
            "multihost": block,
            "qps_mean": round(qps_h, 2),
            "qps_std": round(float(np.std(nq_run / np.asarray(times))), 2),
            # a topology line can be the published mode only when it ran
            # alone; it carries no MFU of its own
            "mfu": None,
            "roofline": _rl.attribute(model, qps_h),
        }

    def sweep_join():
        """Opt-in bulk kNN-join measurement (knn_tpu.join): every row
        of a host-resident query set A joined against the placed corpus
        through the double-buffered superblock stream, then the SAME
        rows pushed through a looped, per-block-synchronous serving
        loop on the same placement — the amortization claim
        (rows/s + overlap_ratio vs baseline_rows_per_s) as one
        validated ``join`` artifact block.  rows_per_s hoists to the
        line as ``join_rows_per_s`` via the schema catalog."""
        from knn_tpu.join import knn_join
        from knn_tpu.join.artifact import validate_join_block
        from knn_tpu.obs import roofline as _rl

        rows = JOIN_ROWS or max(NQ, 4 * BATCH)
        reps = -(-rows // NQ)
        qa = np.tile(queries, (reps, 1))[:rows] if reps > 1 \
            else queries[:rows]
        sb = JOIN_SUPERBLOCK or None
        # warm run compiles the stream program (and fixes the resolved
        # superblock for the baseline), then RUNS timed joins
        d_j, i_j, jstats = knn_join(prog, qa, mode="stream",
                                    superblock_rows=sb, depth=JOIN_DEPTH)
        sb_rows = int(jstats["superblock_rows"])
        walls, overlaps = [], []
        for _ in range(RUNS):
            _, _, jstats = knn_join(prog, qa, mode="stream",
                                    superblock_rows=sb_rows,
                                    depth=JOIN_DEPTH)
            walls.append(jstats["wall_s"])
            overlaps.append(jstats["overlap_ratio"])
        wall = float(np.mean(walls))
        rows_per_s = round(rows / wall, 2)

        # looped-serving baseline: the same superblocks through
        # prog.search, every block's result fetched before the next
        # dispatch — the pre-join serving pattern (no dispatch-ahead,
        # no donated buffers), so the delta IS the overlap machinery
        def pad_to(chunk):
            pad = sb_rows - chunk.shape[0]
            return np.pad(chunk, ((0, pad), (0, 0))) if pad else chunk

        np.asarray(prog.search(pad_to(qa[:sb_rows]))[0])  # warm, blocked
        base_walls = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            for lo in range(0, rows, sb_rows):
                d_b, _ = prog.search(pad_to(qa[lo:lo + sb_rows]))
                np.asarray(d_b)  # block: serving fetches per request
            base_walls.append(time.perf_counter() - t0)
        baseline = round(rows / float(np.mean(base_walls)), 2)

        block = {
            "join_version": _join_version(),
            "mode": jstats["mode"],
            "rows": int(jstats["rows"]),
            "k": int(jstats["k"]),
            "superblock_rows": sb_rows,
            "depth": int(jstats["depth"]),
            "order": jstats["order"],
            "superblocks": int(jstats["superblocks"]),
            "db_segments": int(jstats["db_segments"]),
            "dispatches": int(jstats["dispatches"]),
            "rows_per_s": rows_per_s,
            "overlap_ratio": overlaps[-1],
            "wall_s": round(wall, 4),
            "plan": jstats["plan"],
            "baseline_rows_per_s": baseline,
            "speedup_vs_serving": (round(rows_per_s / baseline, 3)
                                   if baseline else None),
        }
        errs = validate_join_block(block)
        if errs:
            block["validation_errors"] = errs
        entry = {"join": block}
        try:
            # the MODEL_VERSION-7 amortized-db-bytes model for this
            # exact join shape: terms.h2d + the join sub-block, the
            # analytic rows/s ceiling the measured rate is judged by
            model = _rl.join_cost_model(
                n_a=rows, n_b=N, d=DIM, k=K, superblock_rows=sb_rows,
                selector="exact",
                db_segment_rows=int(jstats["plan"].get(
                    "db_segment_rows", 0)),
                device_kind=getattr(dev, "device_kind", ""),
                backend=backend,
                num_devices=len(mesh.devices.ravel()))
            entry["roofline"] = _rl.attribute(model, rows_per_s)
        except Exception as e:  # noqa: BLE001 — advisory only
            entry["roofline"] = {"error": f"{type(e).__name__}: {e}"}
        return entry

    def _join_version():
        from knn_tpu.join.artifact import JOIN_VERSION

        return JOIN_VERSION

    def sweep_quality():
        """Opt-in shadow-audit quality measurement (knn_tpu.obs.audit):
        a short serving replay with the audit sampler forced to rate
        1.0, so every request's served top-k is re-scored off the
        serving path against the f64 exact oracle over the full placed
        corpus.  The block is the audited quality ledger — recall@k,
        rank displacement, distance error — as one validated
        ``quality`` artifact block; audit_recall_at_k hoists to the
        line via the schema catalog."""
        from knn_tpu import obs as _obs
        from knn_tpu.obs import audit as _audit
        from knn_tpu.obs import names as _names
        from knn_tpu.serving.engine import ServingEngine

        if not _obs.enabled():
            return {"quality": {
                "error": "telemetry disabled (KNN_TPU_OBS=0): the "
                         "audit sampler cannot arm"}}
        saved = {k: os.environ.get(k)
                 for k in (_audit.AUDIT_RATE_ENV,
                           _audit.AUDIT_BUDGET_ENV)}
        os.environ[_audit.AUDIT_RATE_ENV] = "1.0"
        os.environ.pop(_audit.AUDIT_BUDGET_ENV, None)
        _audit.reset_auditor()
        t0 = time.perf_counter()
        try:
            min_bucket = SERVING_MIN_BUCKET or max(1, BATCH // 32)
            eng = ServingEngine(prog, min_bucket=min_bucket,
                                max_bucket=BATCH)
            eng.warmup()
            rng_q = np.random.default_rng(1234)
            handles = []
            for _ in range(QUALITY_REQUESTS):
                s = int(rng_q.integers(1, BATCH + 1))
                lo = int(rng_q.integers(0, max(1, NQ - s)))
                handles.append(eng.submit(queries[lo:lo + s]))
            for h in handles:
                h.result()
            aud = _audit.get_auditor()
            drained = aud.drain(timeout=120.0)
            summ = aud.summary()
            disp = _obs.histogram(_names.AUDIT_RANK_DISPLACEMENT,
                                  tenant="-").summary()
            derr = _obs.histogram(_names.AUDIT_DISTANCE_ERROR,
                                  tenant="-").summary()
            recall = _obs.histogram(_names.AUDIT_RECALL,
                                    tenant="-").summary()
            block = {
                "quality_version": _audit.QUALITY_VERSION,
                "audit_rate": summ["rate"],
                "audit_sampled_requests": summ["sampled_requests"],
                "audit_replayed_queries": summ["replayed_queries"],
                "audit_deficient_queries": summ["deficient_queries"],
                "audit_dropped_records":
                    int(sum(summ["dropped"].values())),
                "audit_recall_at_k":
                    (round(float(recall["mean"]), 6)
                     if recall.get("window") else None),
                "audit_rank_displacement_p99":
                    (round(float(disp["p99"]), 4)
                     if disp.get("window") else None),
                "audit_distance_rel_error_p99":
                    (round(float(derr["p99"]), 8)
                     if derr.get("window") else None),
                "wall_s": round(time.perf_counter() - t0, 4),
            }
            if not drained:
                block["error"] = ("audit drain timed out with "
                                  "replays still pending")
            return {"quality": block}
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            _audit.reset_auditor()

    def sweep_fleet():
        """Opt-in fleet-plane measurement (knn_tpu.obs.fleet): merge
        the fleet's telemetry and emit the validated ``fleet`` artifact
        block.  With ``KNN_TPU_FLEET_MEMBERS`` set it collects the
        live endpoints; otherwise it snapshots THIS process and merges
        the one-member fleet — the offline proof that the collect ->
        merge -> block pipeline holds on every bench host."""
        import tempfile as _tempfile

        from knn_tpu import obs as _obs
        from knn_tpu.obs import fleet as _fleet

        t0 = time.perf_counter()
        if not _obs.enabled():
            block = _fleet.artifact_block(_fleet.live_fleet_report())
        elif _fleet.fleet_members():
            block = _fleet.artifact_block(_fleet.fleet_report())
        else:
            with _tempfile.TemporaryDirectory() as d:
                _obs.write_json_snapshot(
                    os.path.join(d, "self.json"))
                block = _fleet.artifact_block(
                    _fleet.fleet_report(snapshot_dir=d))
        block["wall_s"] = round(time.perf_counter() - t0, 4)
        return {"fleet": block}

    def roofline_for_mode(mode, entry):
        """The selector's ``roofline`` block (knn_tpu.obs.roofline):
        analytic ceiling q/s + bound class for the config this mode
        actually ran, attributed against its device-phase rate where
        one was measured (the harness-independent number) else the
        end-to-end mean.  On a cpu/unknown device the model falls back
        to the generic-CPU peaks with ``estimated: true`` — a flagged
        estimate beats an attribution-blind line.  Failure-proof: a
        model gap degrades to an error field, never kills the line."""
        from knn_tpu.obs import roofline as _rl

        common = dict(n=N, d=DIM, k=K,
                      device_kind=getattr(dev, "device_kind", ""),
                      backend=backend,
                      num_devices=len(mesh.devices.ravel()))
        pb = entry.get("phase_breakdown") or {}
        if mode == "certified_pallas":
            pq_kw = {}
            if KNOBS["precision"] == "pq":
                # price the pq arm at the geometry the placement
                # actually trained (env-tunable), not the module default
                try:
                    plq = prog._pq_placement()
                    pq_kw = dict(pq_dsub=int(plq["dsub"]),
                                 pq_ncodes=int(plq["ncodes"]))
                except Exception:  # noqa: BLE001 — advisory pricing only
                    pass
            model = _rl.pallas_cost_model(
                nq=NQ, precision=KNOBS["precision"],
                kernel=KNOBS["kernel"], grid_order=KNOBS["grid_order"],
                binning=KNOBS["binning"], tile_n=KNOBS["tile_n"],
                block_q=KNOBS["block_q"], survivors=KNOBS["survivors"],
                margin=MARGIN, **pq_kw, **common)
            measured = pb.get("device_qps") or entry.get("qps_mean")
        elif mode == "serving":
            # the bucketed engine dispatches the exact-search program;
            # max_bucket chunks bound its db passes — an optimistic
            # ceiling for the variable-batch trace
            model = _rl.xla_cost_model(
                nq=int(entry.get("trace_queries") or NQ),
                selector="exact", dtype=DTYPE, batch=BATCH, **common)
            measured = entry.get("sustained_qps")
        else:
            model = _rl.xla_cost_model(
                nq=NQ, selector="exact" if mode == "exact" else "approx",
                dtype=DTYPE, batch=BATCH,
                margin=MARGIN if mode == "exact" else APPROX_MARGIN,
                **common)
            measured = pb.get("device_qps") or entry.get("qps_mean")
        att = _rl.attribute(model, measured)
        # e2e attribution beside the device-phase one, where they differ
        if measured and entry.get("qps_mean") and \
                measured != entry["qps_mean"] and att.get("ceiling_qps"):
            att["roofline_pct_e2e"] = round(
                entry["qps_mean"] / att["ceiling_qps"], 4)
        return att

    sweeps = {
        "exact": sweep_exact,
        "certified_approx": sweep_certified("approx"),
        "certified_pallas": sweep_certified("pallas"),
    }
    #: database passes per query: coarse matmul, + the certificate's
    #: count-below matmul for the counted certified mode (fallback
    #: excluded — it is rare, per-run stats record it).  The pallas
    #: kernel self-certifies: ONE pass.
    passes = {"exact": 1, "certified_approx": 2, "certified_pallas": 1}

    def phase_breakdown_pallas():
        """Where a certified_pallas sweep's wall time goes (VERDICT r2
        missing item 4): device compute vs device->host transfer vs host
        rank-correction, measured on the full query set with the already-
        compiled program.  Also measures the harness's D2H bandwidth —
        through the dev relay it is the binding resource, NOT the TPU."""
        from knn_tpu.ops.refine import rank_correct_runs

        import jax as _jax

        from knn_tpu.parallel.sharded import DB_AXIS, unpack_certified

        # the same program+geometry the timed sweep ran (ONE source of
        # truth: ShardedKNN._pallas_setup, fed the same resolved KNOBS)
        pp, m, w = prog._pallas_setup(
            MARGIN, KNOBS["tile_n"], KNOBS["precision"],
            bin_w=KNOBS["bin_w"],
            survivors=KNOBS["survivors"], block_q=KNOBS["block_q"],
            final_select=KNOBS["final_select"],
            binning=KNOBS["binning"],
            final_recall_target=KNOBS["final_recall_target"],
            grid_order=KNOBS["grid_order"], kernel=KNOBS["kernel"],
        )
        pb_queries = queries
        if METRIC == "cosine":
            # the pallas program computes l2 against the unit-normalized
            # placed db; search_certified normalizes queries internally,
            # so this timing probe must feed it the same normalized form
            from knn_tpu.parallel.sharded import _row_normalize_f64

            pb_queries = _row_normalize_f64(queries)
        t0 = time.perf_counter()
        qp, _ = prog._place_queries(pb_queries)
        _jax.block_until_ready(qp)
        h2d = time.perf_counter() - t0
        # the operand tail is precision-shaped (int8: the quantized
        # placement; f32: the scalar norm bound) — ONE home,
        # ShardedKNN._pallas_operands, so this probe can never call the
        # program with the wrong arity
        ops_tail = prog._pallas_operands(KNOBS["precision"])
        out = pp(qp, prog._tp, *ops_tail)
        _jax.block_until_ready(out)  # warm/compiled
        t0 = time.perf_counter()
        out = pp(qp, prog._tp, *ops_tail)
        _jax.block_until_ready(out)  # device-only time, no transfer
        dev = time.perf_counter() - t0
        t0 = time.perf_counter()
        # the sweep's fetch: ONE packed array (the relay charges a fixed
        # ~65 ms latency per transfer call on top of its bandwidth)
        packed = np.asarray(out)
        xfer = time.perf_counter() - t0
        gi, tight, badf, dk = unpack_certified(packed[:NQ], K, w, True)
        t0 = time.perf_counter()
        # the certified space's arrays: for cosine that is the unit-
        # normalized pair (prog's host train is the placed/normalized db)
        rank_correct_runs(gi, tight, K, pb_queries, prog._host_train(),
                          d32k=dk.astype(np.float64))
        host = time.perf_counter() - t0
        mb = packed.nbytes / 1e6
        # kernel launch accounting (ONE home for the arithmetic:
        # ops.pallas_knn): the tiled grid re-launches its pipelined body
        # once per train tile; the streaming kernel is one launch per
        # (batch, shard) whose in-kernel DMA loop covers every tile
        from knn_tpu.ops.pallas_knn import (
            BIN_W as _BIN_W,
            TILE_N as _TILE_N,
            effective_tile,
            kernel_launches_per_batch,
        )

        shard_rows = prog._tp.shape[0] // prog.mesh.shape[DB_AXIS]
        eff = effective_tile(
            shard_rows, KNOBS["tile_n"] or _TILE_N,
            KNOBS["bin_w"] or _BIN_W, KNOBS["survivors"],
            KNOBS["binning"], m + 2)
        return {
            "kernel": KNOBS["kernel"],
            "db_tiles_per_shard": -(-shard_rows // eff),
            "kernel_launches_per_batch_shard": kernel_launches_per_batch(
                KNOBS["kernel"], shard_rows, eff),
            "h2d_queries_s": round(h2d, 4),
            "device_s": round(dev, 4),
            "device_qps": round(NQ / dev, 1),
            "d2h_transfer_s": round(xfer, 4),
            "d2h_mb": round(mb, 2),
            "d2h_mbps": round(mb / xfer, 1) if xfer > 0 else None,
            "host_rank_correct_s": round(host, 4),
            # structured transport provenance (the machine-usable form
            # of the prose note below): h2d/d2h ride the dev harness's
            # relay, NOT TPU PCIe, and no latency correction is
            # applied — so the calibration reconciler
            # (knn_tpu.obs.traceread.sample_from_phases) excludes the
            # transfer phases from device-term residuals by reading
            # this field instead of string-matching the note
            "transport": {"kind": "dev_relay",
                          "latency_corrected": False},
            "note": ("sweep wall ~= h2d + device + d2h + rank_correct + "
                     "repair; h2d/d2h ride the dev harness's relay "
                     "(~65 ms latency per call + ~19-38 MB/s), not TPU "
                     "PCIe — device_qps is the harness-independent rate"),
        }

    def phase_breakdown_counted(mode):
        """Device-phase rate for the counted selectors (VERDICT r4 item
        6: ``mfu_device`` for EVERY selector, not just the pallas
        winner): the coarse select program alone for ``exact``, coarse +
        count-below for ``certified_approx`` — no host refine, no result
        transfer.  Measured at the SWEEP's batch shape (BATCH queries):
        both timed sweeps dispatch BATCH-sized device programs, so this
        is a compile-cache hit and the rate describes the geometry the
        sweep actually ran — an NQ-shaped probe would silently pay a
        fresh compile over the relay AND measure a different batch."""
        import jax as _jax

        from knn_tpu.parallel.sharded import (
            DB_AXIS,
            _count_program,
            _knn_program,
            _row_normalize_f64,
        )

        qb = queries[:BATCH]
        if qb.shape[0] < BATCH:  # one compiled shape, like the sweeps
            qb = np.pad(qb, ((0, BATCH - qb.shape[0]), (0, 0)))
        shard_rows = prog._tp.shape[0] // prog.mesh.shape[DB_AXIS]
        if mode == "exact":
            coarse = _knn_program(
                prog.mesh, coarse_k, METRIC, prog.merge, prog.n_train,
                prog.train_tile, prog._dtype_key)
            qp, _ = prog._place_queries(qb)
            launches = [lambda: coarse(qp, prog._tp)]
        else:
            qn = _row_normalize_f64(qb) if METRIC == "cosine" else qb
            cert_metric = "l2" if METRIC == "cosine" else METRIC
            m_c = min(K + APPROX_MARGIN, prog.n_train, shard_rows)
            coarse = _knn_program(
                prog.mesh, m_c, cert_metric, prog.merge, prog.n_train,
                prog.train_tile, prog._dtype_key, "approx",
                recall_target=APPROX_RT)
            count = _count_program(prog.mesh, prog.n_train, prog.train_tile)
            qp, _ = prog._place_queries(qn)
            # threshold values don't change the count pass's FLOPs
            thr = np.zeros(qp.shape[0], np.float32)
            launches = [lambda: coarse(qp, prog._tp),
                        lambda: count(qp, prog._tp, thr)]
        dev = 0.0
        for launch in launches:
            _jax.block_until_ready(launch())  # warm (a sweep cache hit)
            t0 = time.perf_counter()
            _jax.block_until_ready(launch())
            dev += time.perf_counter() - t0
        return {"device_s": round(dev, 4),
                "device_batch": BATCH,
                "device_qps": round(BATCH / dev, 1)}

    def soundness_gate():
        """Small-scale compiled certified search vs the float64 oracle —
        the same check scripts/archive/tpu_session.py runs, embedded so a bare
        ``python bench.py`` artifact carries its own soundness verdict.
        ~20 s once per run at 128-dim configs, scaling ~linearly with
        dim (the host float64 oracle dominates); KNN_BENCH_GATE=0
        skips."""
        from knn_tpu.ops.certified import host_exact_knn
        from knn_tpu.ops.pallas_knn import TILE_N as TILE_N_DEFAULT
        from knn_tpu.ops.pallas_knn import knn_search_pallas

        g_rng = np.random.default_rng(7)
        # gate at the CONFIG's full dim: dim > DIM_CHUNK takes the
        # kernel's multi-chunk scratch-accumulation path (gist's 960),
        # which a 128-dim gate would never exercise — and the round-3
        # lesson is that soundness failures are build-detail dependent
        g_db = g_rng.random((100_000, DIM), dtype=np.float32) * 128
        # tie pressure: duplicate rows + a near-tie pileup exercise the
        # lexicographic rank correction and the near-tie mask in the
        # compiled build (a different failure class than the round-3
        # bounds-accumulation miss)
        g_db[50_000:50_050] = g_db[:50]
        g_db[70_000:70_020] = g_db[100] + 1e-3
        g_q = g_rng.random((24, DIM), dtype=np.float32) * 128
        g_q[0] = g_db[100] + 5e-4  # lands inside the pileup
        # a query ON a duplicated pair forces EXACT ties across distant
        # db tiles (rows 0 and 50_000 live ~3 tiles apart at the default
        # geometry) into the top-k — the cross-tile lexicographic merge
        # path a same-tile pileup alone never reaches
        g_q[1] = g_db[0] + 5e-4
        g_k = min(K, 100)
        _, oracle = host_exact_knn(g_db, g_q, g_k)
        # gate the SAME kernel configuration the sweeps run (precision,
        # geometry, final select, db-streaming strategy) — the round-3
        # failure was build-detail dependent, so checking a different
        # program proves nothing
        _, idx, g_stats = knn_search_pallas(
            g_q, g_db, g_k, precision=KNOBS["precision"],
            tile_n=KNOBS["tile_n"] or TILE_N_DEFAULT,
            bin_w=KNOBS["bin_w"],
            survivors=KNOBS["survivors"], block_q=KNOBS["block_q"],
            final_select=KNOBS["final_select"],
            binning=KNOBS["binning"],
            final_recall_target=KNOBS["final_recall_target"],
            grid_order=KNOBS["grid_order"], kernel=KNOBS["kernel"],
        )
        return {
            "pallas_gate_ok": bool((idx == oracle).all()),
            "gate_queries": int(g_q.shape[0]),
            "gate_rows": int(g_db.shape[0]),
            "gate_stats": g_stats,
        }

    gate = None
    if (os.environ.get("KNN_BENCH_GATE", "1") != "0"
            and backend not in ("cpu",)
            and "certified_pallas" in modes):
        try:
            _vlog("compiled soundness gate ...")
            gate = soundness_gate()
            _vlog(f"gate: {gate['pallas_gate_ok']}")
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            gate = {"pallas_gate_ok": None,
                    "gate_error": f"{type(e).__name__}: {e}"}

    trace_dir = ARGS.trace_dir
    results = {}
    for mode in modes:
        entry = {}
        if mode == "serving":
            # trace replay, not a fixed-shape timed sweep: its entry
            # carries sustained_qps + latency percentiles instead of
            # qps_mean, and never competes for the headline number
            try:
                entry = sweep_serving()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            if "error" not in entry:
                try:
                    entry["roofline"] = roofline_for_mode(mode, entry)
                except Exception as e:  # noqa: BLE001 — advisory only
                    entry["roofline"] = {
                        "error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "knee":
            # open-loop saturation sweep: like serving, a traffic-shape
            # measurement, never a headline-number competitor
            try:
                entry = sweep_knee()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "mutation":
            # live mixed read+write traffic across compaction swaps: a
            # traffic-shape measurement, never a headline competitor
            try:
                entry = sweep_mutation()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "ivf":
            # probed-tier tradeoff measurement (bytes saved vs fallback
            # repairs): a pruning-shape line, never a headline competitor
            try:
                entry = sweep_ivf()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "multihost":
            # hierarchical-merge + host-RAM tier measurement: a
            # topology-shape line, never a headline-number competitor
            try:
                entry = sweep_multihost()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "join":
            # bulk kNN-join throughput (rows/s, not q/s): an offline
            # batch-shape line, never a headline-number competitor
            try:
                entry = sweep_join()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "quality":
            # shadow-audit quality replay: a correctness ledger, never
            # a throughput competitor
            try:
                entry = sweep_quality()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        if mode == "fleet":
            # cross-host telemetry merge: an observability ledger,
            # never a throughput competitor
            try:
                entry = sweep_fleet()
            except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
                entry = {"error": f"{type(e).__name__}: {e}"}
            results[mode] = entry
            continue
        try:
            fn = sweeps[mode]
            _vlog(f"mode {mode}: recall check + warm ...")
            if oracle_idx is not None:
                idx_sub, _ = fn(sub)  # also compiles every program involved
                entry["recall_at_k"] = recall_at_k(idx_sub, oracle_idx)
            # warm the exact shapes the timed runs use: the pallas mode
            # runs ONE full-size batch (different program shape than the
            # BATCH-sized pipeline), so it must warm on the full set or
            # run 1 silently pays its compile
            fn(queries if mode == "certified_pallas" else queries[:BATCH])
            times = []
            stats = None
            _vlog(f"mode {mode}: timed runs ...")
            for _ in range(RUNS):
                t0 = time.perf_counter()
                _, stats = fn(queries)
                times.append(time.perf_counter() - t0)
            _vlog(f"mode {mode}: done ({round(NQ / float(np.mean(times)), 1)} q/s)")
            # one extra instrumented run, OUTSIDE the timed stats —
            # profiler overhead must not skew the headline numbers.
            # obs.profiler wraps jax.profiler.trace, so the artifact is
            # the on-chip XLA trace, TensorBoard-loadable from
            # <dir>/<mode>; gated by --trace-dir/KNN_BENCH_TRACE (this
            # explicit flag) or the ambient KNN_TPU_PROFILE_DIR
            from knn_tpu.obs import profiler as _profiler

            with _profiler.device_trace(mode, base_dir=trace_dir) as tdir:
                if tdir is not None:
                    t0 = time.perf_counter()
                    fn(queries)
                    entry["traced_run_s"] = round(time.perf_counter() - t0, 4)
                    entry["trace_dir"] = tdir
            times = np.asarray(times)
            qps = NQ / times
            flops = 2.0 * NQ * N * DIM * passes[mode]
            entry.update({
                "qps_mean": round(float(qps.mean()), 2),
                "qps_std": round(float(qps.std()), 2),
                "qps_best": round(float(qps.max()), 2),
                "time_mean_s": round(float(times.mean()), 4),
                "runs": RUNS,
                "mfu": (None if peak is None
                        else round(flops / float(times.mean()) / peak, 4)),
            })
            if stats is not None:
                entry["certified_stats"] = stats
            if mode in ("exact", "certified_approx"):
                pb = phase_breakdown_counted(mode)
                entry["phase_breakdown"] = pb
                if peak is not None and pb.get("device_s"):
                    # the probe ran device_batch queries, not NQ
                    bflops = (2.0 * pb["device_batch"] * N * DIM
                              * passes[mode])
                    entry["mfu_device"] = round(
                        bflops / pb["device_s"] / peak, 4)
            if mode == "certified_pallas":
                pb = phase_breakdown_pallas()
                entry["phase_breakdown"] = pb
                if peak is not None and pb.get("device_s"):
                    # MFU of the device phase alone — what the chip does,
                    # net of the harness's D2H relay
                    entry["mfu_device"] = round(
                        flops / pb["device_s"] / peak, 4
                    )
                # label-only consumers (the reference's actual workload:
                # predicted labels) skip the distance transfer
                lo_fn = sweep_certified("pallas", return_distances=False)
                lo_fn(queries)  # warm the distance-free fetch path
                lo_times = []
                for _ in range(min(RUNS, 3)):
                    t0 = time.perf_counter()
                    lo_fn(queries)
                    lo_times.append(time.perf_counter() - t0)
                entry["qps_labels_only"] = round(
                    NQ / float(np.mean(lo_times)), 2
                )
        except Exception as e:  # noqa: BLE001 — one bad mode must not kill the line
            entry["error"] = f"{type(e).__name__}: {e}"
        if "qps_mean" in entry:
            # percent-of-roofline attribution beside mfu/mfu_device on
            # EVERY measured selector line — the named gap the kernel
            # campaign attacks per config
            try:
                entry["roofline"] = roofline_for_mode(mode, entry)
            except Exception as e:  # noqa: BLE001 — advisory only
                entry["roofline"] = {"error": f"{type(e).__name__}: {e}"}
        results[mode] = entry

    def _ok(m):
        e = results.get(m, {})
        if "qps_mean" not in e:
            return False
        r = e.get("recall_at_k")
        if r is None:
            # no oracle: certified modes are exact by construction, but the
            # exact path's margin heuristic is unverified -> not headline
            return m.startswith("certified")
        return r == 1.0

    ranked = sorted((m for m in results if _ok(m)),
                    key=lambda m: -results[m]["qps_mean"])
    recall_flag = {}
    if not ranked:
        # no mode with verified exactness; publish the fastest measured one
        # honestly flagged rather than nothing.  Distinguish "no oracle to
        # check against" from "checked and missed neighbors".
        ranked = sorted((m for m in results if "qps_mean" in results[m]),
                        key=lambda m: -results[m]["qps_mean"])
        if ranked:
            r = results[ranked[0]].get("recall_at_k")
            recall_flag = (
                {"recall_unverified": True} if r is None
                else {"recall_below_one": True}
            )
    if not ranked:
        _fail("all_modes", {m: results[m].get("error", "?") for m in results},
              selectors=results, backend=backend)
    best = ranked[0]
    qps = results[best]["qps_mean"]
    # vs_baseline from the SAME rounded fields the JSON carries, so the
    # artifact is internally reproducible (round-2 advisor finding)
    cpu_qps_r = round(cpu_qps, 2) if cpu_qps else None

    # the chip's own rate, net of the harness's host<->device relay —
    # surfaced top-level because on the dev harness the relay, not the
    # TPU, binds the end-to-end number.  Hoisted from the WINNING
    # mode's phase breakdown (every selector carries one since r4), so
    # the sentinel's device_phase_qps baseline judges device-phase
    # regressions separately from end-to-end qps on every line — not
    # only when certified_pallas wins; the pallas breakdown remains the
    # fallback for lines whose winner has no device probe
    dev_qps = (results.get(best, {})
               .get("phase_breakdown", {}).get("device_qps")
               or results.get("certified_pallas", {})
               .get("phase_breakdown", {}).get("device_qps"))
    # the pointer applies to any relay-down FALLBACK run (backend fell
    # to cpu without being asked for), shrunken or not — explicit env
    # size overrides must not lose the hardware evidence
    fell_back = (backend == "cpu"
                 and os.environ.get("KNN_BENCH_PLATFORM") != "cpu")
    curated_ref = curated_tpu_reference() if fell_back else None
    # the winning mode's roofline verdict rides top-level: the full
    # block for readers, plus hoisted roofline_pct/bound_class so the
    # sentinel's curated-field baselines and the artifact refresher
    # read them flat.  Lines whose mfu is null (cpu backend / unknown
    # device kind) still get a block — computed from the generic-CPU
    # fallback peaks and flagged roofline_estimated — so CPU microbench
    # lines stop being attribution-blind.
    rl_top = results[best].get("roofline")
    if not isinstance(rl_top, dict) or "ceiling_qps" not in rl_top:
        try:
            rl_top = roofline_for_mode(best, results[best])
        except Exception as e:  # noqa: BLE001 — advisory only
            rl_top = {"error": f"{type(e).__name__}: {e}"}
    rl_fields = {"roofline": rl_top}
    # quantization provenance: precision rides top-level on EVERY line so
    # the precision-ladder A/B lines (int8 / int4 / pq vs the f32 family)
    # are self-describing and the artifact refresher can curate them
    # separately per arm; quantized lines add the certified bound's worst
    # case over this query set and the scales dtype (the reproducibility
    # trio the ISSUE names), and pq lines carry their codebook geometry
    quant_prov = {"precision": KNOBS["precision"]}
    if KNOBS["precision"] in ("int8", "int4"):
        try:
            from knn_tpu.ops import quantize as _qz

            plq = (prog._int8_placement() if KNOBS["precision"] == "int8"
                   else prog._int4_placement())
            qb_prov = queries
            if METRIC == "cosine":
                from knn_tpu.parallel.sharded import _row_normalize_f64

                qb_prov = _row_normalize_f64(queries)
            eps = _qz.score_error_bound(
                qb_prov, plq["stats"], offset=plq["offset"])
            quant_prov["quant_bound_max"] = float(np.max(eps))
            quant_prov["quant_scales_dtype"] = "float32"
        except Exception as e:  # noqa: BLE001 — provenance must not kill the line
            quant_prov["quant_bound_error"] = f"{type(e).__name__}: {e}"
    elif KNOBS["precision"] == "pq":
        # pq lines additionally carry the cataloged "pq" artifact block
        # (knn_tpu.analysis.artifacts): codebook geometry + the
        # certified bound's worst case, validated/swept like every
        # other bench block
        try:
            from knn_tpu.analysis import widths as _widths
            from knn_tpu.ops import pq as _pqm
            from knn_tpu.ops.pq_artifact import PQ_VERSION

            plq = prog._pq_placement()
            qb_prov = queries
            if METRIC == "cosine":
                from knn_tpu.parallel.sharded import _row_normalize_f64

                qb_prov = _row_normalize_f64(queries)
            eps = _pqm.score_error_bound_pq(qb_prov, plq["stats"])
            quant_prov["quant_bound_max"] = float(np.max(eps))
            quant_prov["quant_scales_dtype"] = "float32"
            nsub = _widths.pq_nsub(DIM, int(plq["dsub"]))
            quant_prov["pq"] = {
                "pq_version": PQ_VERSION,
                "dsub": int(plq["dsub"]),
                "ncodes": int(plq["ncodes"]),
                "nsub": nsub,
                "lut_bytes": _widths.pq_lut_bytes(
                    int(qb_prov.shape[0]), DIM, dsub=int(plq["dsub"]),
                    ncodes=int(plq["ncodes"])),
                "bound_max": float(np.max(eps)),
                "queries": int(qb_prov.shape[0]),
            }
        except Exception as e:  # noqa: BLE001 — provenance must not kill the line
            quant_prov["quant_bound_error"] = f"{type(e).__name__}: {e}"
            quant_prov.setdefault("pq", {})["error"] = (
                f"{type(e).__name__}: {e}")
    line = {
        "metric": f"knn_qps_{CONFIG}_n{N}_d{DIM}_k{K}",
        "value": qps,
        "unit": "queries/s",
        "vs_baseline": round(qps / cpu_qps_r, 2) if cpu_qps_r else None,
        "mode": best,
        "device_phase_qps": dev_qps,
        # the variable-batch-size traffic numbers (serving mode): hoisted
        # so the sustained rate + tail latency are readable without
        # digging into the selectors table
        **({
            "serving_sustained_qps": results["serving"].get("sustained_qps"),
            "serving_latency_ms": results["serving"].get("latency_ms"),
            # rides top-level only when measured (KNN_BENCH_OBS_OVERHEAD):
            # the refresher curates it with the line, stale-guard and all
            **({"obs_overhead_pct":
                results["serving"]["obs_overhead_pct"]}
               if "obs_overhead_pct" in results["serving"] else {}),
        } if results.get("serving", {}).get("sustained_qps") else {}),
        # the measured latency-vs-throughput knee (opt-in knee mode):
        # the block rides the line; knee_qps is hoisted by the
        # catalog-driven loop below
        **({"loadgen_knee": results["knee"]["loadgen_knee"]}
           if results.get("knee", {}).get("loadgen_knee") else {}),
        # the mixed read+write traffic proof (opt-in mutation mode):
        # block on the line, admitted p99 hoisted below
        **({"mutation": results["mutation"]["mutation"]}
           if results.get("mutation", {}).get("mutation") else {}),
        # the probed-tier tradeoff (opt-in ivf mode): block on the
        # line; ivf_qps + recall hoist via the catalog loop below
        **({"ivf": results["ivf"]["ivf"]}
           if results.get("ivf", {}).get("ivf") else {}),
        # the multi-host topology measurement (opt-in multihost mode):
        # block + the mode entry's own qps (not a block field); the
        # host-tier sweep count hoists below
        **({
            "multihost": results["multihost"]["multihost"],
            "multihost_qps": results["multihost"].get("qps_mean"),
        } if results.get("multihost", {}).get("multihost") else {}),
        # the bulk kNN-join measurement (opt-in join mode): block on
        # the line; rows_per_s hoists below as join_rows_per_s
        **({"join": results["join"]["join"]}
           if results.get("join", {}).get("join") else {}),
        # the shadow-audit quality ledger (opt-in quality mode): block
        # on the line; audit_recall_at_k hoists via the catalog loop
        **({"quality": results["quality"]["quality"]}
           if results.get("quality", {}).get("quality") else {}),
        **(gate or {}),
        "recall_at_k": results[best].get("recall_at_k"),
        **recall_flag,
        "compute_dtype": DTYPE,
        "metric_fn": metric_label,
        "runs": RUNS,
        "qps_std": results[best]["qps_std"],
        "mfu": results[best]["mfu"],
        # explicit null-MFU provenance (unknown device kind vs cpu
        # backend) so baseline curation can key on MFU where it exists
        **({"mfu_reason": mfu_reason} if mfu_reason else {}),
        "peak_flops_assumed": peak,
        **rl_fields,
        "selectors": results,
        "cpu_baseline_qps": cpu_qps_r,
        "cpu_baseline_cached": _CPU_CACHE_USED,
        "cpu_queries": CPU_QUERIES,
        "cpu_per_query_s": round(cpu_per_q_s, 4) if cpu_per_q_s else None,
        "devices": len(mesh.devices.ravel()),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "backend": backend,
        # set when the CPU fallback shrank the workload so the line
        # lands inside a driver timeout — NOT comparable to TPU lines
        # (the metric name carries the actual n/dim/k)
        **({"cpu_fallback_shrunk": True} if cpu_shrunk else {}),
        # the round's curated hardware line for this config (a POINTER,
        # not a measurement of this run): a relay-down fallback line
        # still carries the real TPU evidence
        **({"curated_tpu_line": curated_ref} if curated_ref else {}),
        # the winning mode's actual batch: the pallas path runs ONE
        # full-size batch (sweep_certified passes batch_size=None)
        "batch": NQ if best == "certified_pallas" else BATCH,
        "train_tile": tile,
        # the EFFECTIVE pallas/approx tuning knobs, so a curated artifact
        # line is reproducible from the line itself (ADVICE r2+r3); the
        # tuning block records where each run's knobs came from
        # (persisted autotuner winner vs defaults vs env overrides)
        "pallas_knobs": {**KNOBS, "batch": PALLAS_BATCH, "margin": MARGIN},
        **quant_prov,
        "tuning": TUNE_INFO,
        "approx_knobs": {"recall_target": APPROX_RT,
                         "margin": APPROX_MARGIN},
    }
    # table-driven hoists over the artifact-schema catalog
    # (knn_tpu.analysis.artifacts): every cataloged block riding this
    # line contributes its declared top-level keys — roofline_pct/
    # bound_class/roofline_estimated off the winning mode's roofline
    # block, model_residual_pct off an applied calibration overlay,
    # knee_qps, mutation_admitted_p99_ms, hosttier_sweeps,
    # join_rows_per_s — so the
    # sentinel's curated-field baselines and the artifact refresher
    # read them flat.  One loop instead of one stanza per block; a new
    # bench block hoists by declaring, not by editing this file.
    from knn_tpu.analysis.artifacts import apply_scope_hoists

    apply_scope_hoists(line, scope="bench")
    # perf-regression sentinel verdict (knn_tpu.obs.sentinel): this
    # line judged against the robust baseline of its own history —
    # advisory on the line itself (check_tier1 --strict is the gate);
    # jax-free and failure-proof, it can never break the one-JSON-line
    # contract
    try:
        from knn_tpu.obs import sentinel as _sentinel

        line["sentinel"] = _sentinel.verdict_for_line(
            line, repo_dir=os.path.dirname(os.path.abspath(__file__)))
    except Exception as e:  # noqa: BLE001 — verdict must not kill the line
        line["sentinel"] = {"verdict": "error",
                            "error": f"{type(e).__name__}: {e}"}
    _emit(line)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the driver needs one JSON line, always
        _fail("run", f"{type(e).__name__}: {e}",
              tb=traceback.format_exc(limit=3).splitlines()[-3:])
