#!/usr/bin/env python
"""Benchmark: brute-force exact KNN queries/sec on a SIFT1M-shaped workload
(1M x 128 database, k=100 — BASELINE.json config 3), on whatever devices
JAX exposes (the driver runs this on one real TPU chip).

Prints EXACTLY ONE JSON line:
  {"metric": ..., "value": <q/s>, "unit": "queries/s", "vs_baseline": <x>, ...}

``vs_baseline`` compares against the reference-style CPU brute force: the
native C++ backend (knn_tpu/native, the reference program's semantics with
std::thread standing in for its 8 MPI ranks) timed on a query subsample of
the SAME database.  The reference's own published numbers are MNIST-shaped
and machine-specific (BASELINE.md); an in-situ CPU measurement is the
honest denominator.

Compute dtype is auto-selected: bfloat16 matmuls (MXU native) are used only
if they keep recall@k = 1.0 against the float64 CPU oracle on the
subsample; otherwise float32.

Env overrides (testing): KNN_BENCH_N, KNN_BENCH_DIM, KNN_BENCH_K,
KNN_BENCH_NQ, KNN_BENCH_BATCH, KNN_BENCH_TILE, KNN_BENCH_CPU_QUERIES,
KNN_BENCH_DTYPE (skip auto: "float32" | "bfloat16").
"""

import json
import os
import time

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, default))


N = _env_int("KNN_BENCH_N", 1_000_000)
DIM = _env_int("KNN_BENCH_DIM", 128)
K = _env_int("KNN_BENCH_K", 100)
NQ = _env_int("KNN_BENCH_NQ", 4096)
BATCH = _env_int("KNN_BENCH_BATCH", 512)  # sweep winner on v5e (2026-07)
TILE = _env_int("KNN_BENCH_TILE", 131_072)
CPU_QUERIES = _env_int("KNN_BENCH_CPU_QUERIES", 32)
DTYPE = os.environ.get("KNN_BENCH_DTYPE", "auto")
#: Coarse pass fetches K + MARGIN candidates; exact float64 refinement on
#: host re-selects the true top-K (ops.refine).  Margin absorbs float32
#: near-boundary reorderings so recall@K lands at 1.0.
MARGIN = _env_int("KNN_BENCH_MARGIN", 28)


def recall_at_k(pred_idx: np.ndarray, true_idx: np.ndarray) -> float:
    hits = 0
    for p, t in zip(pred_idx, true_idx):
        hits += len(set(p.tolist()) & set(t.tolist()))
    return hits / true_idx.size


def main() -> None:
    from knn_tpu.ops.refine import refine_exact
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN

    rng = np.random.default_rng(0)
    db = (rng.random(size=(N, DIM)) * 128.0).astype(np.float32)
    queries = (rng.random(size=(NQ, DIM)) * 128.0).astype(np.float32)

    # --- CPU baseline (native C++ backend, all hardware threads) ----------
    cpu_qps = None
    oracle_idx = None
    sub = queries[:CPU_QUERIES]
    try:
        from knn_tpu import native

        if native.available():
            t0 = time.perf_counter()
            _, oracle_idx = native.knn_search(db, sub, K, "l2", num_threads=8)
            cpu_qps = CPU_QUERIES / (time.perf_counter() - t0)
    except Exception:
        pass

    # --- TPU path: coarse top-(K+MARGIN) on device, exact refine on host --
    mesh = make_mesh()  # all devices; (1,1) on a single chip
    tile = min(TILE, N)
    coarse_k = min(K + MARGIN, N)

    def build(dtype):
        return ShardedKNN(db, mesh=mesh, k=coarse_k, metric="l2",
                          train_tile=tile, compute_dtype=dtype)

    def run_sub(prog):
        _, ci = prog.search(sub)
        _, ri = refine_exact(db, sub, np.asarray(ci), K)
        return ri

    # dtype choice: explicit env wins; "auto" promotes to bfloat16 only when
    # the oracle confirms recall 1.0.  Exactly one program stays resident —
    # each holds a full device placement of the database.
    if DTYPE == "bfloat16":
        chosen, prog = "bfloat16", build("bfloat16")
    elif DTYPE == "auto" and oracle_idx is not None:
        bf_prog = build("bfloat16")
        if recall_at_k(run_sub(bf_prog), oracle_idx) == 1.0:
            chosen, prog = "bfloat16", bf_prog  # reuse: compiled + placed
        else:
            chosen = "float32"
            del bf_prog  # free its HBM placement before the real build
            prog = build(None)
    else:
        chosen, prog = "float32", build(None)

    recall = None
    if oracle_idx is not None:
        recall = recall_at_k(run_sub(prog), oracle_idx)

    def batches():
        for lo in range(0, NQ, BATCH):
            chunk = queries[lo : lo + BATCH]
            pad = BATCH - chunk.shape[0]
            yield lo, np.pad(chunk, ((0, pad), (0, 0))) if pad else chunk, pad

    # warmup on the first padded chunk: the timed loop must hit a warm shape
    _, warm_chunk, _ = next(batches())
    prog.search(warm_chunk)[0].block_until_ready()

    t0 = time.perf_counter()
    coarse = [(lo, prog.search(chunk), pad) for lo, chunk, pad in batches()]
    results = []
    for lo, (d, i), pad in coarse:  # refine overlaps later batches' device work
        i = np.asarray(i)
        if pad:
            i = i[:-pad]
        results.append(refine_exact(db, queries[lo : lo + i.shape[0]], i, K))
    elapsed = time.perf_counter() - t0
    qps = NQ / elapsed

    result = {
        "metric": f"exact_knn_qps_n{N}_d{DIM}_k{K}",
        "value": round(qps, 2),
        "unit": "queries/s",
        "vs_baseline": round(qps / cpu_qps, 2) if cpu_qps else None,
        "recall_at_k": recall,
        "compute_dtype": chosen,
        "cpu_baseline_qps": round(cpu_qps, 2) if cpu_qps else None,
        "devices": len(mesh.devices.ravel()),
        "batch": BATCH,
        "train_tile": tile,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
